"""Quantized LLM serving (ISSUE 16): weight-only int8/int4 decode +
int8 KV-cache pages.

The acceptance posture is two-tier, mirroring the paper's CNN
quantization story lifted to serving:

- WITHIN the quantized engine everything stays BIT-parity: spec-decode
  vs plain greedy, migrated vs unmigrated continuations, prefix-cache
  CoW vs cold prefill — quantization changes the numbers, not the
  invariants, because every path reads the same integer weights and the
  same per-page KV scales.
- ACROSS the fp32 <-> quantized boundary the oracle is greedy-token
  AGREEMENT (thresholded >= 0.99 for the int8 rung), because bit-parity
  is definitionally gone the moment weights drop bits.

Kernel-level: the fused dequant-matmul under
``MXNET_QUANT_MATMUL=interpret`` must be bit-exact against the XLA
reference (they compute the identical formula op-for-op), and the wire
format (pack_session v2) must round-trip scales with their own CRC and
still read v1 blobs.
"""
from __future__ import annotations

import numpy as onp
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu import serving
from mxnet_tpu.models import decoder
from mxnet_tpu.ops.pallas import quant_matmul as qmm
from mxnet_tpu.serving.kvcache import (PageAllocator, pack_session,
                                       unpack_session)
from mxnet_tpu.serving.quantize import (QuantizedLM, calibrate_kv_ranges,
                                        quantize_lm, quantize_params)

pytestmark = [pytest.mark.quant, pytest.mark.llm]

VOCAB = 128

# the agreement battery: varied prompts, enough tokens that a 0.99
# threshold tolerates exactly one greedy tie-flip across the battery
PROMPTS = [[1, 2, 3, 4, 5], [7, 7, 7, 7], [3, 1, 4, 1, 5, 9, 2, 6],
           [11, 13, 17, 19, 23], [2, 4, 6, 8, 10, 12], [42, 17]]
NEW = 20


@pytest.fixture(scope="module")
def lm():
    return decoder.decoder_tiny_lm(seed=0, vocab_size=VOCAB)


def make_engine(lm, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("max_ctx", 64)
    return serving.DecodeEngine(lm, name="llm", **kw)


def greedy_oracle(model, prompt, n):
    """Token-by-token full forward.  Works for the fp model AND a
    QuantizedLM — full_forward dispatches quantized leaves through
    quant_matmul, so this is the same-weights oracle for the engine."""
    params, cfg = model.jax_params(), model.config
    toks = list(prompt)
    for _ in range(n):
        logits = decoder.full_forward(params, cfg,
                                      jnp.asarray([toks], jnp.int32))
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


def run_battery(eng, prompts=PROMPTS, n=NEW):
    futs = [eng.submit(list(p), n) for p in prompts]
    return [f.result(timeout=300)["tokens"] for f in futs]


def agreement(a, b):
    """Positionwise greedy-token agreement across a battery."""
    tot = hit = 0
    for xa, xb in zip(a, b):
        tot += max(len(xa), len(xb))
        hit += sum(1 for x, y in zip(xa, xb) if x == y)
    return hit / max(tot, 1)


def tf_agreement(eng, fp_tokens, prompts=PROMPTS, max_ctx=64):
    """Teacher-forced greedy agreement: for every position of the fp
    engine's trajectories, ask ``eng`` for ONE next token off the same
    prefix and compare.  Free-running comparison is the wrong oracle
    for a quantized engine — a single near-tie flip cascades the rest
    of the trajectory into a different attractor, so one flipped token
    would read as ~17% disagreement.  Per-step agreement is what the
    quantization actually changes."""
    futs, want = [], []
    for p, t in zip(prompts, fp_tokens):
        hist = list(p) + t
        for i in range(len(t)):
            pre = hist[:len(p) + i]
            if len(pre) + 1 > max_ctx:
                break
            futs.append(eng.submit(pre, 1))
            want.append(t[i])
    got = [f.result(timeout=300)["tokens"][0] for f in futs]
    return sum(1 for g, w in zip(got, want) if g == w) / len(want)


@pytest.fixture(scope="module")
def fp_tokens(lm):
    eng = make_engine(lm)
    try:
        return run_battery(eng)
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# quantize / dequantize units
# ---------------------------------------------------------------------------
def test_w8_round_trip_per_channel():
    rng = onp.random.RandomState(0)
    w = rng.randn(24, 32).astype("float32") * rng.rand(24, 1).astype("f")
    w[3] = 0.0                                  # dead output channel
    qw = qmm.quantize_w8(w)
    assert qw.q.dtype == jnp.int8 and qw.s.dtype == jnp.float32
    assert qw.q.shape == (24, 32) and qw.s.shape == (24,)
    assert int(jnp.abs(qw.q).max()) <= 127
    deq = onp.asarray(qmm.dequantize_weight(qw))
    # symmetric rounding error is at most half a step per channel
    err = onp.abs(deq - w).max(axis=1)
    assert (err <= onp.asarray(qw.s) * 0.5 + 1e-7).all()
    # zero channel: scale 1.0 (no div-by-zero), codes exactly zero
    assert float(qw.s[3]) == 1.0 and not onp.asarray(qw.q[3]).any()


def test_w4_pack_groups_and_shapes():
    rng = onp.random.RandomState(1)
    w = rng.randn(16, 64).astype("float32")
    qw = qmm.quantize_w4(w, group=16)
    assert qw.q.dtype == jnp.uint8 and qw.q.shape == (16, 32)
    assert qw.s.shape == (16, 4)                # 64 / 16 groups
    # the group size is derivable from the shapes (wire/TP invariant)
    assert 2 * qw.q.shape[1] // qw.s.shape[1] == 16
    vals = onp.asarray(qmm.unpack_int4(qw.q))
    assert vals.min() >= -7 and vals.max() <= 7  # symmetric codebook
    deq = onp.asarray(qmm.dequantize_weight(qw))
    step = onp.repeat(onp.asarray(qw.s), 16, axis=1)
    assert (onp.abs(deq - w) <= step * 0.5 + 1e-7).all()
    # pack/unpack is lossless for in-range codes
    codes = rng.randint(-7, 8, size=(8, 10)).astype("int8")
    assert (onp.asarray(qmm.unpack_int4(qmm.pack_int4(jnp.asarray(codes))))
            == codes).all()
    # group clamps to a divisor of the input dim
    assert qmm.group_for(48, 128) == 48 and qmm.group_for(64, 24) == 8
    with pytest.raises(ValueError, match="even"):
        qmm.quantize_w4(w[:, :63])


def test_quantize_params_structure(lm):
    params = lm.jax_params()
    qp = quantize_params(params, "int8")
    for lp, qlp in zip(params["layers"], qp["layers"]):
        for kind in decoder._QUANT_KINDS:
            assert isinstance(qlp[kind], qmm.QuantW8)
            assert qlp[kind].q.shape == lp[kind].shape  # (O, I) storage
        # everything else untouched (embeddings/biases/norms stay fp32)
        assert qlp["bq"] is lp["bq"] and qlp["ln1g"] is lp["ln1g"]
    assert qp["embed"] is params["embed"]
    with pytest.raises(ValueError, match="mode"):
        quantize_params(params, "int2")
    # int4 under tp=2: row-parallel leaves (wo, w2) shrink the group to
    # the per-shard input dim so scales never straddle shards
    qp4 = quantize_params(params, "int4", group=128, tp=2)
    lp4 = qp4["layers"][0]
    units = lm.config.units
    assert 2 * lp4["wo"].q.shape[1] // lp4["wo"].s.shape[1] \
        == qmm.group_for(units // 2, 128)
    assert 2 * lp4["wq"].q.shape[1] // lp4["wq"].s.shape[1] \
        == qmm.group_for(units, 128)            # column-parallel: full I


def test_quantize_lm_wrapper(lm):
    q = quantize_lm(lm, "int8")
    assert isinstance(q, QuantizedLM)
    assert q.config is lm.config and q.quant_token() == ("int8",)
    # re-quantizing unwraps to fp first (modes don't compose)
    q4 = quantize_lm(q, "int4", group=32)
    assert q4.model is lm and q4.quant_token() == ("int4", 32)
    with pytest.raises(ValueError, match="mode"):
        quantize_lm(lm, "fp8")
    # params cached per tp degree only where groups depend on it
    assert q.jax_params(tp=1) is q.jax_params(tp=2)      # int8: tp-blind
    assert q4.jax_params(tp=1) is not q4.jax_params(tp=2)


# ---------------------------------------------------------------------------
# fused kernel vs XLA reference (interpret-mode bit-exactness oracle)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("mode", ["int8", "int4"])
def test_quant_matmul_interpret_bit_exact(monkeypatch, mode):
    rng = onp.random.RandomState(2)
    x = jnp.asarray(rng.randn(4, 64).astype("float32"))
    w = rng.randn(48, 64).astype("float32")
    qw = (qmm.quantize_w8(w) if mode == "int8"
          else qmm.quantize_w4(w, group=16))
    ref = qmm.quant_matmul_reference(x, qw)
    monkeypatch.setenv("MXNET_QUANT_MATMUL", "interpret")
    before = qmm.trace_counts["quant_matmul"]
    out = qmm.quant_matmul(x, qw)
    assert qmm.last_path == "pallas-interpret"
    assert qmm.trace_counts["quant_matmul"] == before + 1
    assert onp.asarray(out).tobytes() == onp.asarray(ref).tobytes()
    # leading dims flow through
    x3 = jnp.asarray(rng.randn(2, 3, 64).astype("float32"))
    assert qmm.quant_matmul(x3, qw).shape == (2, 3, 48)


def test_quant_matmul_disabled_uses_reference(monkeypatch):
    monkeypatch.setenv("MXNET_QUANT_MATMUL", "0")
    assert qmm.quant_mode() is None
    qw = qmm.quantize_w8(onp.eye(8, dtype="float32") * 2.0)
    out = qmm.quant_matmul(jnp.ones((1, 8), jnp.float32), qw)
    assert qmm.last_path == "xla"
    assert onp.allclose(onp.asarray(out), 2.0)
    monkeypatch.setenv("MXNET_QUANT_MATMUL", "interpret")
    assert qmm.quant_mode() == "interpret"


# ---------------------------------------------------------------------------
# engine parity: same-weights bit-parity, cross-precision agreement
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("mode,group", [("int8", None), ("int4", 32)])
def test_engine_bit_parity_with_quantized_oracle(lm, mode, group):
    """fp KV pages + quantized weights: the engine's chunked-prefill +
    paged-decode path must reproduce the quantized full_forward oracle
    token-for-token — quantization must not break PR-7's core
    invariant."""
    qlm = quantize_lm(lm, mode, group=group or 128)
    eng = make_engine(lm, quantize=mode,
                      **({"quant_group": group} if group else {}))
    try:
        for p in PROMPTS[:3]:
            got = eng.submit(list(p), 8).result(60)["tokens"]
            assert got == greedy_oracle(qlm, p, 8)
        st = eng.stats()
        assert st["quant"]["weights"] == mode
        assert st["quant"]["kv_dtype"] == "float32"
    finally:
        eng.stop()
    assert eng.alloc.num_used == 0
    eng.alloc.check_leaks()


def test_int8_engine_agreement_battery(lm, fp_tokens):
    """The serving acceptance gate: int8 weights + int8 KV pages agree
    with the fp32 engine on >= 99% of greedy tokens across the
    battery."""
    eng = make_engine(lm, quantize="int8", kv_dtype="int8")
    try:
        score = tf_agreement(eng, fp_tokens)
        st = eng.stats()
    finally:
        eng.stop()
    assert score >= 0.99
    assert st["quant"] == {"weights": "int8", "group": None,
                           "kv_dtype": "int8", "tokens_resident": 0}
    eng.alloc.check_leaks()


def test_int4_engine_agreement_battery(lm, fp_tokens):
    # int4 is the lossier rung: the gate is looser but still must track
    # the fp engine on a strong majority of greedy steps
    eng = make_engine(lm, quantize="int4", quant_group=32)
    try:
        score = tf_agreement(eng, fp_tokens)
    finally:
        eng.stop()
    assert score >= 0.9
    eng.alloc.check_leaks()


def test_int8_kv_only_agreement(lm, fp_tokens):
    # kv_dtype=int8 with fp weights: per-page scale latch alone
    eng = make_engine(lm, kv_dtype="int8")
    try:
        score = tf_agreement(eng, fp_tokens)
        st = eng.stats()
    finally:
        eng.stop()
    assert score >= 0.99
    assert st["quant"]["weights"] is None
    assert st["quant"]["kv_dtype"] == "int8"
    eng.alloc.check_leaks()


def test_quantized_decode_not_fused(lm, monkeypatch):
    # the fused decode cell is an fp-weight program: quantized engines
    # must fall back to the tower path even if fusion is requested
    monkeypatch.setenv("MXNET_DECODE_FUSED", "interpret")
    eng = make_engine(lm, quantize="int8")
    try:
        assert eng.decode_fused_mode is None
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# in-engine invariants survive quantization: spec, prefix CoW, capacity
# ---------------------------------------------------------------------------
@pytest.mark.spec
@pytest.mark.parametrize("k", [1, 2])
def test_speculative_bit_parity_in_quantized_engine(lm, k):
    """Spec-vs-plain stays BIT-identical inside the quantized engine:
    draft and verify read the same integer weights and the same KV page
    scales (the page-start latch makes scales write-order-invariant)."""
    plain = make_engine(lm, quantize="int8", kv_dtype="int8")
    spec = make_engine(lm, quantize="int8", kv_dtype="int8",
                       speculate=True, spec_k=k, drafter="ngram")
    try:
        t_plain = run_battery(plain, PROMPTS[:4], 12)
        t_spec = run_battery(spec, PROMPTS[:4], 12)
        assert t_spec == t_plain
        assert spec.stats()["speculative"]["drafter"] == "ngram"
    finally:
        plain.stop()
        spec.stop()
    for e in (plain, spec):
        assert e.alloc.num_used == 0
        e.alloc.check_leaks()


@pytest.mark.migration
def test_prefix_cache_cow_on_int8_pages(lm):
    """Prefix sharing + CoW forks carry int8 pages: page codes AND their
    scales alias on a hit and copy together on the fork, so warm paths
    stay bit-identical to cold ones within the quantized engine."""
    cold_eng = make_engine(lm, quantize="int8", kv_dtype="int8")
    eng = make_engine(lm, quantize="int8", kv_dtype="int8",
                      prefix_cache=True)
    sys_prompt = list(range(1, 17))             # 2 full pages
    tails = [[20, 21], [30, 31], [20, 21, 60, 61]]
    try:
        cold = [cold_eng.submit(sys_prompt + t, 6).result(60)["tokens"]
                for t in tails]
        warm = [eng.submit(sys_prompt + t, 6).result(60)["tokens"]
                for t in tails]
        assert warm == cold
        snap = eng.metrics.snapshot()["models"]["llm"]
        assert snap["counters"]["prefix_hits_total"] >= 1
        eng.alloc.check_leaks()
    finally:
        cold_eng.stop()
        eng.stop()
    for e in (cold_eng, eng):
        assert e.alloc.num_used == 0
        e.alloc.check_leaks()


def test_int8_kv_capacity_ratio(lm):
    """The capacity win the int8 KV pages exist for: bytes per cached
    token (codes + amortized per-page scales) is >= 1.9x smaller than
    fp32 pages, so a fixed pool byte budget holds >= 1.9x the resident
    sessions."""
    fp = make_engine(lm)
    q = make_engine(lm, kv_dtype="int8")
    try:
        fpb = fp.alloc.stats()["kv_bytes_per_token"]
        qb = q.alloc.stats()["kv_bytes_per_token"]
        assert fpb / qb >= 1.9
        assert q.alloc.stats()["kv_dtype"] == "int8"
        assert fp.alloc.stats()["kv_dtype"] == "float32"
        # tokens-resident gauge: parked session holds its pages (the
        # final emitted token was never fed back, so its KV isn't
        # cached: 4 prompt + 3 decoded inputs)
        q.submit([1, 2, 3, 4], 4, session="s").result(60)
        assert q.stats()["quant"]["tokens_resident"] == 7
        snap = q.metrics.snapshot()["models"]["llm"]["generate"]
        assert snap["kv_bytes_per_token"] == qb
        assert "kv_tokens_resident" in snap
    finally:
        fp.stop()
        q.stop()


# ---------------------------------------------------------------------------
# migration: int8 pages travel; dtype mismatch is typed, never garbage
# ---------------------------------------------------------------------------
@pytest.mark.migration
def test_export_import_int8_bit_identical(lm):
    e1 = make_engine(lm, quantize="int8", kv_dtype="int8")
    e2 = make_engine(lm, quantize="int8", kv_dtype="int8")
    prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3]
    try:
        r1 = e1.submit(prompt, 5, session="mig").result(60)
        blob = e1.export_session("mig")
        meta, k, v, ks, vs = unpack_session(blob, with_scales=True)
        assert k.dtype == onp.int8 and ks is not None
        assert ks.shape == k.shape[:3] and ks.dtype == onp.float32
        e2.import_session(blob)
        # the continuation both engines would produce is the SAME
        # program over the SAME codes + scales: bit-identical
        r1b = e1.submit([7], 5, session="mig", resume=True).result(60)
        # (re-import after e1 advanced: fresh copy of the original blob)
        e2.submit([7], 5, session="mig", resume=True).result(60)
        e2b = make_engine(lm, quantize="int8", kv_dtype="int8")
        try:
            e2b.import_session(blob)
            r2 = e2b.submit([7], 5, session="mig", resume=True).result(60)
            assert r2["tokens"] == r1b["tokens"]
        finally:
            e2b.stop()
    finally:
        e1.stop()
        e2.stop()
    for e in (e1, e2):
        assert e.alloc.num_used == 0
        e.alloc.check_leaks()


@pytest.mark.migration
def test_kv_dtype_mismatch_typed_error(lm):
    qe = make_engine(lm, kv_dtype="int8")
    fe = make_engine(lm)
    try:
        qe.submit([1, 2, 3], 3, session="a").result(60)
        fe.submit([1, 2, 3], 3, session="b").result(60)
        qblob = qe.export_session("a")
        fblob = fe.export_session("b")
        with pytest.raises(ValueError, match="does not match"):
            fe.import_session(qblob)            # int8 blob -> fp engine
        with pytest.raises(ValueError, match="does not match"):
            qe.import_session(fblob)            # fp blob -> int8 engine
    finally:
        qe.stop()
        fe.stop()
    for e in (qe, fe):
        assert e.alloc.num_used == 0
        e.alloc.check_leaks()


# ---------------------------------------------------------------------------
# tensor parallelism: the agreement oracle composes with TP
# ---------------------------------------------------------------------------
@pytest.mark.multichip
@pytest.mark.parametrize("mode,group", [("int8", None), ("int4", 16)])
def test_quantized_engine_tensor_parallel(lm, mode, group):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices "
                    "(XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    from mxnet_tpu.parallel.shardcfg import ShardingConfig
    scfg = ShardingConfig.for_transformer(mesh_shape=(4, 2),
                                          axis_names=("dp", "tp"))
    kw = {"quant_group": group} if group else {}
    one = make_engine(lm, quantize=mode, kv_dtype="int8", **kw)
    tp = make_engine(lm, quantize=mode, kv_dtype="int8", sharding=scfg,
                     **kw)
    try:
        assert tp.tp == 2
        t1 = run_battery(one, PROMPTS[:4], 12)
        # TP reorders the row-parallel reduction, so the oracle is the
        # same thresholded per-step agreement as the fp<->quant boundary
        assert tf_agreement(tp, t1, prompts=PROMPTS[:4]) >= 0.99
        st = tp.stats()
        assert st["quant"]["weights"] == mode
        assert st["sharding"]["tp"] == 2
    finally:
        one.stop()
        tp.stop()
    for e in (one, tp):
        assert e.alloc.num_used == 0
        e.alloc.check_leaks()


# ---------------------------------------------------------------------------
# wire format v2: scales blob + own CRC, v1 back-compat
# ---------------------------------------------------------------------------
def test_pack_session_v2_round_trip_and_scales_crc():
    rng = onp.random.RandomState(3)
    k = rng.randint(-127, 128, size=(2, 2, 3, 8, 4)).astype("int8")
    v = rng.randint(-127, 128, size=(2, 2, 3, 8, 4)).astype("int8")
    ks = rng.rand(2, 2, 3).astype("float32")
    vs = rng.rand(2, 2, 3).astype("float32")
    meta = {"sid": "s", "pos": 17, "history": [1, 2]}
    blob = pack_session(meta, k, v, k_scales=ks, v_scales=vs)
    m2, k2, v2, ks2, vs2 = unpack_session(blob, with_scales=True)
    assert m2 == meta
    assert k2.tobytes() == k.tobytes() and v2.tobytes() == v.tobytes()
    assert ks2.tobytes() == ks.tobytes() and vs2.tobytes() == vs.tobytes()
    assert k2.dtype == onp.int8 and ks2.dtype == onp.float32
    # a flipped byte in the scales tail trips the SCALES CRC, not the
    # payload one (independent failure domains)
    bad = bytearray(blob)
    bad[-1] ^= 0xFF
    with pytest.raises(ValueError, match="scales CRC"):
        unpack_session(bytes(bad), with_scales=True)
    with pytest.raises(ValueError, match="truncated"):
        unpack_session(blob[:-8], with_scales=True)
    # both-or-neither: half a scale pair is a caller bug
    with pytest.raises(ValueError):
        pack_session(meta, k, v, k_scales=ks)


def test_pack_session_v1_compat():
    rng = onp.random.RandomState(4)
    k = rng.randn(2, 2, 3, 8, 4).astype("float32")
    v = rng.randn(2, 2, 3, 8, 4).astype("float32")
    blob = pack_session({"sid": "s"}, k, v)
    # no scales -> the v1 wire image: header carries no kv_dtype key, a
    # v1 reader decodes it unchanged
    hlen = int(onp.frombuffer(blob[4:8], "<u4")[0])
    assert b'"kv_dtype"' not in blob[8:8 + hlen]
    m, k2, v2 = unpack_session(blob)
    assert k2.tobytes() == k.tobytes()
    # a v1 blob read through the v2 API reports no scales
    m, k2, v2, ks, vs = unpack_session(blob, with_scales=True)
    assert ks is None and vs is None


def test_allocator_scales_pool_accounting():
    a = PageAllocator(total_pages=9, page_size=4, kv_dtype="int8",
                      page_bytes=128, scale_page_bytes=16)
    st = a.stats()
    assert st["kv_dtype"] == "int8"
    assert st["scale_page_bytes"] == 16
    # 8 usable pages (page 0 reserved); scales pool counted in
    assert st["pool_bytes"] == 8 * (128 + 16)
    assert st["kv_bytes_per_token"] == (128 + 16) / 4
    a.alloc("s", 2)
    assert a.stats()["used_bytes"] == 2 * (128 + 16)
    a.free("s")
    a.check_leaks()
    with pytest.raises(ValueError, match="kv_dtype"):
        PageAllocator(total_pages=4, page_size=4, kv_dtype="fp8")


# ---------------------------------------------------------------------------
# config knobs, replica spec plumbing, calibration diagnostic
# ---------------------------------------------------------------------------
def test_env_knobs_boot_quantized_engine(lm, monkeypatch):
    monkeypatch.setenv("MXNET_QUANT_WEIGHTS", "int4")
    monkeypatch.setenv("MXNET_QUANT_GROUP", "32")
    monkeypatch.setenv("MXNET_QUANT_KV", "int8")
    eng = make_engine(lm)
    try:
        st = eng.stats()["quant"]
        assert st["weights"] == "int4" and st["group"] == 32
        assert st["kv_dtype"] == "int8"
    finally:
        eng.stop()
    with pytest.raises(ValueError):
        make_engine(lm, kv_dtype="int4")        # KV ladder is int8-only
    with pytest.raises(ValueError):
        make_engine(lm, quantize="fp8")


def test_config_registry_covers_quant_knobs():
    from mxnet_tpu import config
    d = config.describe()
    for knob in ("MXNET_QUANT_WEIGHTS", "MXNET_QUANT_KV",
                 "MXNET_QUANT_GROUP", "MXNET_QUANT_MATMUL"):
        assert knob in d and d[knob].status == "honored"
        assert d[knob].consumer


def test_replica_resolve_quant_block():
    from mxnet_tpu.serving.replica import resolve_quant
    assert resolve_quant(None) == {}
    assert resolve_quant({}) == {}
    assert resolve_quant({"weights": "int8", "kv": "int8"}) \
        == {"quantize": "int8", "kv_dtype": "int8"}
    assert resolve_quant({"weights": "int4", "group": 64}) \
        == {"quantize": "int4", "quant_group": 64}


def test_steplat_census_quant_arm_and_fp_fused_unchanged():
    """The dispatch-bill gate the bench row pins: the quantized decode
    step runs the per-op tower (the fused cell is an fp-weight
    program), and the fp fused path keeps its historical 6-launch
    program — the quant code paths must not perturb it."""
    from benchmark.steplat import decode_steplat
    d = decode_steplat(measure=False, fused_mode="interpret")
    assert d["fused"]["launches_per_step"] == 6
    assert d["fused"]["pallas_per_group"] == 1.0
    assert d["quant_int8"]["fused"] is False
    assert d["quant_int8"]["launches_per_step"] > 0
    assert d["quant_int8"]["pallas_per_step"] == 0  # CPU: XLA reference


def test_calibrate_kv_ranges_diagnostic(lm):
    rng = onp.random.RandomState(5)
    batches = [rng.randint(0, VOCAB, size=(2, 12)) for _ in range(3)]
    th = calibrate_kv_ranges(lm, batches)
    L = lm.config.num_layers
    assert set(th) == {"L%d/%s" % (i, kv)
                      for i in range(L) for kv in ("k", "v")}
    for lo, hi in th.values():
        assert hi > 0 and hi >= lo
    # works on the wrapped model too (observes the fp forward)
    assert set(calibrate_kv_ranges(quantize_lm(lm), batches[:1])) == set(th)
