"""Pipeline parallelism tests on the virtual 8-device CPU mesh
(conftest sets xla_force_host_platform_device_count=8)."""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.parallel import Mesh
from mxnet_tpu.parallel.pipeline import PipelineRunner, pipeline_apply


def _mesh(n, axis="pp"):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip("needs %d devices" % n)
    return Mesh(onp.array(devs[:n]), (axis,))


def _mlp_stage(w, x):
    return jnp.tanh(x @ w)


def test_pipeline_matches_sequential():
    S, B, D = 4, 8, 16
    mesh = _mesh(S)
    rng = onp.random.RandomState(0)
    ws = [jnp.asarray(rng.randn(D, D).astype(onp.float32) * 0.3)
          for _ in range(S)]
    x = jnp.asarray(rng.randn(B, D).astype(onp.float32))

    runner = PipelineRunner([_mlp_stage] * S, mesh)
    y = runner.apply(ws, x, n_microbatches=4)

    ref = x
    for w in ws:
        ref = jnp.tanh(ref @ w)
    onp.testing.assert_allclose(onp.asarray(y), onp.asarray(ref),
                                rtol=2e-5, atol=1e-5)


def test_pipeline_heterogeneous_stages():
    S, B, D = 2, 4, 8
    mesh = _mesh(S)
    rng = onp.random.RandomState(1)
    w0 = jnp.asarray(rng.randn(D, D).astype(onp.float32) * 0.3)
    w1 = jnp.asarray(rng.randn(D, D).astype(onp.float32) * 0.3)

    def stage0(w, x):
        return jax.nn.relu(x @ w)

    def stage1(w, x):
        return x @ w + 1.0

    x = jnp.asarray(rng.randn(B, D).astype(onp.float32))
    y = pipeline_apply([stage0, stage1], [w0, w1], x, mesh,
                       n_microbatches=2)
    ref = jax.nn.relu(x @ w0) @ w1 + 1.0
    onp.testing.assert_allclose(onp.asarray(y), onp.asarray(ref),
                                rtol=2e-5, atol=1e-5)


@pytest.mark.slow
def test_pipeline_differentiable():
    """Gradients flow through the pipelined program (training path)."""
    S, B, D = 2, 4, 8
    mesh = _mesh(S)
    rng = onp.random.RandomState(2)
    ws = [jnp.asarray(rng.randn(D, D).astype(onp.float32) * 0.3)
          for _ in range(S)]
    x = jnp.asarray(rng.randn(B, D).astype(onp.float32))
    runner = PipelineRunner([_mlp_stage] * S, mesh)

    def loss(ws):
        return jnp.sum(runner.apply(ws, x, n_microbatches=2) ** 2)

    g = jax.grad(loss)(ws)

    def ref_loss(ws):
        h = x
        for w in ws:
            h = jnp.tanh(h @ w)
        return jnp.sum(h ** 2)

    g_ref = jax.grad(ref_loss)(ws)
    for a, b in zip(g, g_ref):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=1e-4, atol=1e-4)


def test_pipeline_microbatch_validation():
    mesh = _mesh(2)
    runner = PipelineRunner([_mlp_stage] * 2, mesh)
    w = [jnp.zeros((4, 4))] * 2
    with pytest.raises(AssertionError, match="not divisible"):
        runner.apply(w, jnp.zeros((5, 4)), n_microbatches=2)


# ---------------------------------------------------------------------------
# PipelineTrainer: Trainer-grade GPipe training (VERDICT r4 #10)
# ---------------------------------------------------------------------------
def test_pipeline_trainer_trains_real_model():
    import time
    import jax
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import np as mxnp, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import Mesh
    from mxnet_tpu.parallel.pipeline import PipelineTrainer

    S, H, B = 4, 64, 64
    mesh = Mesh(onp.array(jax.devices()[:S]), ("pp",))
    mx.random.seed(0)
    prologue = nn.HybridSequential()
    prologue.add(nn.Flatten(), nn.Dense(H, activation="relu",
                                        in_units=28 * 28))
    stages = []
    for _ in range(S):
        st = nn.HybridSequential()
        st.add(nn.Dense(H, activation="relu", in_units=H))
        stages.append(st)
    epilogue = nn.Dense(10, in_units=H)
    x = mxnp.random.uniform(size=(B, 1, 28, 28))
    y = mxnp.random.randint(0, 10, size=(B,))
    for blk in [prologue] + stages + [epilogue]:
        blk.initialize(mx.init.Xavier())
    h = prologue(x)
    for st in stages:
        h = st(h)
    seq_ref = epilogue(h)

    loss_obj = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = PipelineTrainer(prologue, stages, epilogue,
                              lambda o, l: loss_obj(o, l),
                              "sgd", {"learning_rate": 0.03,
                                      "momentum": 0.9},
                              mesh, n_microbatches=8)
    state = trainer.init_state()
    trainer.build_step(donate=False)

    # pipelined forward == sequential execution of the same blocks
    fwd = trainer._forward(state["params"], x._data)
    onp.testing.assert_allclose(onp.asarray(fwd), seq_ref.asnumpy(),
                                rtol=2e-4, atol=2e-5)

    # training decreases loss on a fixed batch
    losses = []
    t0 = time.perf_counter()
    for _ in range(25):
        state, loss = trainer.step(state, x, y)
        losses.append(float(jax.device_get(loss)))
    dt = time.perf_counter() - t0
    assert losses[-1] < losses[0] * 0.9, losses[:3] + losses[-3:]
    # throughput floor: compiled pipelined steps, not per-step recompiles
    # (a loose anti-recompile gate — the box is 1 CPU core and CI may
    # share it with other lanes)
    assert 25 * B / dt > 40, "pipeline step too slow: %.1f img/s" % (
        25 * B / dt)


def test_pipeline_trainer_rejects_heterogeneous_stages():
    import jax
    import numpy as onp
    import pytest
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import Mesh
    from mxnet_tpu.parallel.pipeline import PipelineTrainer

    S = 2
    mesh = Mesh(onp.array(jax.devices()[:S]), ("pp",))
    st1 = nn.HybridSequential(); st1.add(nn.Dense(8, in_units=8))
    st2 = nn.HybridSequential()
    st2.add(nn.Dense(8, in_units=8), nn.Dense(8, in_units=8))
    for b in (st1, st2):
        b.initialize()
    loss_obj = gluon.loss.SoftmaxCrossEntropyLoss()
    with pytest.raises(ValueError, match="structurally identical"):
        PipelineTrainer(None, [st1, st2], None,
                        lambda o, l: loss_obj(o, l),
                        "sgd", {}, mesh)


def test_pipeline_trainer_batchnorm_stats_update():
    """Stages containing BatchNorm train in TRAINING mode: running stats
    move, and the aux updates land back in the state."""
    import jax
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import np as mxnp, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import Mesh
    from mxnet_tpu.parallel.pipeline import PipelineTrainer

    S, H, B = 2, 16, 32
    mesh = Mesh(onp.array(jax.devices()[:S]), ("pp",))
    mx.random.seed(0)
    stages = []
    for _ in range(S):
        st = nn.HybridSequential()
        st.add(nn.Dense(H, in_units=H), nn.BatchNorm(axis=1),
               nn.Activation("relu"))
        stages.append(st)
    epilogue = nn.Dense(4, in_units=H)
    x = mxnp.random.uniform(size=(B, H)) * 3.0 + 1.0
    y = mxnp.random.randint(0, 4, size=(B,))
    for blk in stages + [epilogue]:
        blk.initialize(mx.init.Xavier())
    h = x
    for st in stages:
        h = st(h)
    epilogue(h)

    loss_obj = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = PipelineTrainer(None, stages, epilogue,
                         lambda o, l: loss_obj(o, l),
                         "sgd", {"learning_rate": 0.05}, mesh,
                         n_microbatches=4)
    state = tr.init_state()
    tr.build_step(donate=False)
    rm_keys = [k for k in state["params"]["stages"] if "running_mean" in k]
    w_keys = [k for k in state["params"]["stages"]
              if k.endswith("weight") and "running" not in k]
    assert rm_keys, "BN running stats missing from pipeline state"
    rm_before = onp.asarray(state["params"]["stages"][rm_keys[0]])
    w_before = onp.asarray(state["params"]["stages"][w_keys[0]])
    for i in range(3):
        state, loss = tr.step(state, x, y, key=jax.random.key(i))
    rm_after = onp.asarray(state["params"]["stages"][rm_keys[0]])
    w_after = onp.asarray(state["params"]["stages"][w_keys[0]])
    assert not onp.allclose(rm_before, rm_after), \
        "BatchNorm running stats did not update through the pipeline"
    # regression: the aux write-back must NOT clobber the gradient step —
    # pipelined stage WEIGHTS must train, not just prologue/epilogue
    assert not onp.allclose(w_before, w_after), \
        "pipelined stage weights did not train (aux write-back clobber)"
    assert onp.isfinite(float(jax.device_get(loss)))
