"""Pipeline parallelism tests on the virtual 8-device CPU mesh
(conftest sets xla_force_host_platform_device_count=8)."""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.parallel import Mesh
from mxnet_tpu.parallel.pipeline import PipelineRunner, pipeline_apply


def _mesh(n, axis="pp"):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip("needs %d devices" % n)
    return Mesh(onp.array(devs[:n]), (axis,))


def _mlp_stage(w, x):
    return jnp.tanh(x @ w)


def test_pipeline_matches_sequential():
    S, B, D = 4, 8, 16
    mesh = _mesh(S)
    rng = onp.random.RandomState(0)
    ws = [jnp.asarray(rng.randn(D, D).astype(onp.float32) * 0.3)
          for _ in range(S)]
    x = jnp.asarray(rng.randn(B, D).astype(onp.float32))

    runner = PipelineRunner([_mlp_stage] * S, mesh)
    y = runner.apply(ws, x, n_microbatches=4)

    ref = x
    for w in ws:
        ref = jnp.tanh(ref @ w)
    onp.testing.assert_allclose(onp.asarray(y), onp.asarray(ref),
                                rtol=2e-5, atol=1e-5)


def test_pipeline_heterogeneous_stages():
    S, B, D = 2, 4, 8
    mesh = _mesh(S)
    rng = onp.random.RandomState(1)
    w0 = jnp.asarray(rng.randn(D, D).astype(onp.float32) * 0.3)
    w1 = jnp.asarray(rng.randn(D, D).astype(onp.float32) * 0.3)

    def stage0(w, x):
        return jax.nn.relu(x @ w)

    def stage1(w, x):
        return x @ w + 1.0

    x = jnp.asarray(rng.randn(B, D).astype(onp.float32))
    y = pipeline_apply([stage0, stage1], [w0, w1], x, mesh,
                       n_microbatches=2)
    ref = jax.nn.relu(x @ w0) @ w1 + 1.0
    onp.testing.assert_allclose(onp.asarray(y), onp.asarray(ref),
                                rtol=2e-5, atol=1e-5)


@pytest.mark.slow
def test_pipeline_differentiable():
    """Gradients flow through the pipelined program (training path)."""
    S, B, D = 2, 4, 8
    mesh = _mesh(S)
    rng = onp.random.RandomState(2)
    ws = [jnp.asarray(rng.randn(D, D).astype(onp.float32) * 0.3)
          for _ in range(S)]
    x = jnp.asarray(rng.randn(B, D).astype(onp.float32))
    runner = PipelineRunner([_mlp_stage] * S, mesh)

    def loss(ws):
        return jnp.sum(runner.apply(ws, x, n_microbatches=2) ** 2)

    g = jax.grad(loss)(ws)

    def ref_loss(ws):
        h = x
        for w in ws:
            h = jnp.tanh(h @ w)
        return jnp.sum(h ** 2)

    g_ref = jax.grad(ref_loss)(ws)
    for a, b in zip(g, g_ref):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=1e-4, atol=1e-4)


def test_pipeline_microbatch_validation():
    mesh = _mesh(2)
    runner = PipelineRunner([_mlp_stage] * 2, mesh)
    w = [jnp.zeros((4, 4))] * 2
    with pytest.raises(AssertionError, match="not divisible"):
        runner.apply(w, jnp.zeros((5, 4)), n_microbatches=2)
