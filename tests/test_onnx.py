"""ONNX converters (parity: reference contrib/onnx mx2onnx +
onnx2mx).  The converter logic runs on the protobuf-mirroring model
dict, so structure + numeric round-trip tests run WITHOUT the onnx
package; protobuf file tests engage only when it is installed."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np as mxnp
from mxnet_tpu import sym_api as sym
from mxnet_tpu.contrib.onnx import (export_to_model_dict,
                                    import_from_model_dict)


def _mlp():
    data = sym.var("data", shape=(2, 6), dtype="float32")
    h = sym.Activation(sym.FullyConnected(data, num_hidden=8, name="fc1"),
                       act_type="relu", name="act1")
    out = sym.FullyConnected(h, num_hidden=3, name="fc2")
    rng = onp.random.RandomState(0)
    params = {
        "fc1_weight": rng.randn(8, 6).astype("float32"),
        "fc1_bias": rng.randn(8).astype("float32"),
        "fc2_weight": rng.randn(3, 8).astype("float32"),
        "fc2_bias": rng.randn(3).astype("float32"),
    }
    return out, params


def test_export_model_dict_structure():
    net, params = _mlp()
    model = export_to_model_dict(net, params)
    assert model["opset_import"][0]["version"] >= 13
    g = model["graph"]
    assert [i["name"] for i in g["input"]] == ["data"]
    assert set(params) <= set(g["initializer"])
    ops = [n["op_type"] for n in g["node"]]
    # Flatten (fc1) → Gemm → Relu → Flatten (fc2) → Gemm
    assert ops.count("Gemm") == 2 and "Relu" in ops
    gemm = [n for n in g["node"] if n["op_type"] == "Gemm"][0]
    assert gemm["attribute"]["transB"] == 1
    assert g["output"][0]["shape"] == [2, 3]


def test_mlp_roundtrip_numerics():
    net, params = _mlp()
    model = export_to_model_dict(net, params)
    sym2, arg_params, aux_params = import_from_model_dict(model)
    assert not aux_params
    x = onp.random.RandomState(1).randn(2, 6).astype("float32")
    env = {k: mxnp.array(v) for k, v in params.items()}
    (ref,) = net.eval(data=mxnp.array(x), **env)
    env2 = {k: mxnp.array(v) for k, v in arg_params.items()}
    (out,) = sym2.eval(data=mxnp.array(x), **env2)
    onp.testing.assert_allclose(out.asnumpy(), ref.asnumpy(),
                                rtol=1e-5, atol=1e-5)


def test_convnet_roundtrip_numerics():
    data = sym.var("data", shape=(2, 3, 8, 8), dtype="float32")
    c = sym.Convolution(data, kernel=(3, 3), num_filter=4, pad=(1, 1),
                        stride=(1, 1), name="c1")
    bn = sym.BatchNorm(c, use_global_stats=True, fix_gamma=False,
                       name="bn1")
    act = sym.Activation(bn, act_type="relu", name="a1")
    p = sym.Pooling(act, kernel=(2, 2), stride=(2, 2), pool_type="max",
                    name="p1")
    f = sym.Flatten(p, name="fl1")
    out = sym.softmax(sym.FullyConnected(f, num_hidden=5, name="fc"),
                      axis=-1, name="sm")

    rng = onp.random.RandomState(2)
    params = {
        "c1_weight": (rng.randn(4, 3, 3, 3) * 0.3).astype("float32"),
        "c1_bias": rng.randn(4).astype("float32"),
        "bn1_gamma": rng.uniform(0.5, 1.5, 4).astype("float32"),
        "bn1_beta": rng.randn(4).astype("float32"),
        "bn1_moving_mean": rng.randn(4).astype("float32"),
        "bn1_moving_var": rng.uniform(0.5, 2.0, 4).astype("float32"),
        "fc_weight": rng.randn(5, 64).astype("float32"),
        "fc_bias": rng.randn(5).astype("float32"),
    }
    model = export_to_model_dict(net := out, params)
    ops = [n["op_type"] for n in model["graph"]["node"]]
    for expected in ("Conv", "BatchNormalization", "Relu", "MaxPool",
                     "Flatten", "Gemm", "Softmax"):
        assert expected in ops, ops

    sym2, arg_params, aux_params = import_from_model_dict(model)
    # running stats split into aux (reference onnx2mx behavior)
    assert set(aux_params) == {"bn1_moving_mean", "bn1_moving_var"}
    x = rng.randn(2, 3, 8, 8).astype("float32")
    env = {k: mxnp.array(v) for k, v in params.items()}
    (ref,) = net.eval(data=mxnp.array(x), **env)
    env2 = {k: mxnp.array(v) for k, v in {**arg_params, **aux_params}.items()}
    (got,) = sym2.eval(data=mxnp.array(x), **env2)
    onp.testing.assert_allclose(got.asnumpy(), ref.asnumpy(),
                                rtol=1e-4, atol=1e-4)


def test_arithmetic_and_reduce_roundtrip():
    a = sym.var("a", shape=(3, 4), dtype="float32")
    b = sym.var("b", shape=(3, 4), dtype="float32")
    out = sym.sum((a + b) * a - 2.0, axis=1, keepdims=False)
    model = export_to_model_dict(out, {})
    ops = [n["op_type"] for n in model["graph"]["node"]]
    assert "Add" in ops and "Mul" in ops and "Sub" in ops and \
        "ReduceSum" in ops
    sym2, _ap, _xp = import_from_model_dict(model)
    rng = onp.random.RandomState(3)
    av = rng.randn(3, 4).astype("float32")
    bv = rng.randn(3, 4).astype("float32")
    (ref,) = out.eval(a=mxnp.array(av), b=mxnp.array(bv))
    (got,) = sym2.eval(a=mxnp.array(av), b=mxnp.array(bv))
    onp.testing.assert_allclose(got.asnumpy(), ref.asnumpy(), rtol=1e-5)


def test_embedding_roundtrip():
    tok = sym.var("tok", shape=(2, 5), dtype="int32")
    emb = sym.Embedding(tok, input_dim=11, output_dim=3, name="emb")
    out = sym.sum(emb, axis=-1)
    rng = onp.random.RandomState(4)
    params = {"emb_weight": rng.randn(11, 3).astype("float32")}
    model = export_to_model_dict(out, params)
    assert any(n["op_type"] == "Gather" for n in model["graph"]["node"])
    sym2, ap, _xp = import_from_model_dict(model)
    toks = rng.randint(0, 11, (2, 5)).astype("int32")
    (ref,) = out.eval(tok=mxnp.array(toks),
                      emb_weight=mxnp.array(params["emb_weight"]))
    env = {k: mxnp.array(v) for k, v in ap.items()}
    (got,) = sym2.eval(tok=mxnp.array(toks), **env)
    onp.testing.assert_allclose(got.asnumpy(), ref.asnumpy(), rtol=1e-5)


def test_take_roundtrip_variants():
    rng = onp.random.RandomState(5)
    xv = rng.randn(3, 4).astype("float32")

    def roundtrip(out, **feeds):
        model = export_to_model_dict(out, {})
        sym2, ap, _xp = import_from_model_dict(model)
        env = {k: mxnp.array(v) for k, v in feeds.items()}
        (ref,) = out.eval(**env)
        env.update({k: mxnp.array(v) for k, v in ap.items()})
        (got,) = sym2.eval(**env)
        onp.testing.assert_allclose(got.asnumpy(), ref.asnumpy(),
                                    rtol=1e-5)
        return ref.asnumpy()

    # constant indices + keyword axis
    x = sym.var("x", shape=(3, 4), dtype="float32")
    ref = roundtrip(sym.take(x, [0, 2], axis=1), x=xv)
    onp.testing.assert_allclose(ref, xv[:, [0, 2]], rtol=1e-5)

    # symbolic indices + POSITIONAL axis (regression: axis was read from
    # _extra_pos[1] and silently exported as axis=0) + out-of-range
    # index exercising mode='clip' semantics after export
    i = sym.var("i", shape=(2,), dtype="int32")
    iv = onp.array([1, 9], onp.int32)  # 9 clips to 3
    ref = roundtrip(sym.take(x, i, 1), x=xv, i=iv)
    onp.testing.assert_allclose(ref, xv[:, [1, 3]], rtol=1e-5)

    # axis=None flattens (numpy semantics)
    ref = roundtrip(sym.take(x, i), x=xv, i=iv)
    onp.testing.assert_allclose(ref, xv.ravel()[[1, 9]], rtol=1e-5)

    # mode='wrap'
    ref = roundtrip(sym.take(x, i, 1, "wrap"), x=xv, i=iv)
    onp.testing.assert_allclose(ref, xv[:, [1, 1]], rtol=1e-5)

    # negative axis (regression: the clip bound's Shape lookup rode a
    # negative Gather index, which the importer clipped to dim 0)
    ref = roundtrip(sym.take(x, i, -1), x=xv, i=iv)
    onp.testing.assert_allclose(ref, xv[:, [1, 3]], rtol=1e-5)


def test_l2norm_export_non_channel_mode_raises():
    x = sym.var("x", shape=(2, 3, 4), dtype="float32")
    out = sym.L2Normalization(x, mode="instance")
    with pytest.raises(NotImplementedError, match="channel"):
        export_to_model_dict(out, {})


def test_unconvertible_op_raises_cleanly():
    x = sym.var("x", shape=(4,), dtype="float32")
    weird = sym.Symbol("op", op="npx:gather_nd", inputs=[x, x])
    with pytest.raises(NotImplementedError, match="no ONNX converter"):
        export_to_model_dict(weird, {})


def test_onnx_file_roundtrip(tmp_path):
    onnx = pytest.importorskip("onnx")  # noqa: F841  (absent here; CI w/ onnx runs it)
    from mxnet_tpu.contrib.onnx import export_model, import_model
    net, params = _mlp()
    f = str(tmp_path / "m.onnx")
    export_model(net, params, onnx_file_path=f)
    sym2, ap, xp = import_model(f)
    assert set(ap) == set(params)


def test_export_model_without_onnx_package_gates():
    try:
        import onnx  # noqa: F401
        pytest.skip("onnx installed")
    except ImportError:
        pass
    from mxnet_tpu.contrib.onnx import export_model
    net, params = _mlp()
    with pytest.raises(ImportError, match="export_to_model_dict"):
        export_model(net, params, onnx_file_path="/tmp/x.onnx")


def test_reexport_of_imported_model_is_symmetric():
    # Embedding export emits Cast+Gather; the imported graph (np:astype)
    # must itself be exportable (review finding: converter symmetry)
    tok = sym.var("tok", shape=(2, 3), dtype="int32")
    out = sym.sum(sym.Embedding(tok, input_dim=7, output_dim=2,
                                name="emb"), axis=-1)
    params = {"emb_weight":
              onp.random.RandomState(5).randn(7, 2).astype("float32")}
    model = export_to_model_dict(out, params)
    sym2, ap, _xp = import_from_model_dict(model)
    model2 = export_to_model_dict(sym2, ap)  # must not raise
    assert any(n["op_type"] == "Cast" for n in model2["graph"]["node"])


def test_import_gemm_without_optional_bias():
    w = onp.random.RandomState(6).randn(3, 4).astype("float32")
    model = {
        "ir_version": 8, "producer_name": "t",
        "opset_import": [{"domain": "", "version": 13}],
        "graph": {
            "name": "g",
            "node": [{"op_type": "Gemm", "name": "fc",
                      "input": ["data", "w"], "output": ["fc"],
                      "attribute": {"transB": 1}}],
            "input": [{"name": "data", "elem_type": 1, "shape": [2, 4]}],
            "output": [{"name": "fc", "elem_type": 1, "shape": [2, 3]}],
            "initializer": {"w": w},
        },
    }
    sym2, ap, _xp = import_from_model_dict(model)
    x = onp.random.RandomState(7).randn(2, 4).astype("float32")
    (out,) = sym2.eval(data=mxnp.array(x),
                       **{k: mxnp.array(v) for k, v in ap.items()})
    onp.testing.assert_allclose(out.asnumpy(), x @ w.T, rtol=1e-5,
                                atol=1e-5)


def test_index0_node_exports_as_base_name():
    data = sym.var("data", shape=(2, 4), dtype="float32")
    fc = sym.FullyConnected(data, num_hidden=3, name="fc")
    model = export_to_model_dict(fc[0], {
        "fc_weight": onp.zeros((3, 4), "float32"),
        "fc_bias": onp.zeros(3, "float32")})
    out_name = model["graph"]["output"][0]["name"]
    produced = {o for n in model["graph"]["node"] for o in n["output"]}
    assert out_name in produced  # no dangling "fc:0" reference


# ---------------------------------------------------------------------------
# model-zoo closure (VERDICT r3 #4): every family exports via
# HybridBlock.to_sym and reimports with matching numerics
# ---------------------------------------------------------------------------
def _roundtrip_net(net, x, rtol=2e-3, atol=2e-3, input_dtypes=None):
    ref = net(x)
    ref_list = [r.asnumpy() for r in (ref if isinstance(ref, tuple)
                                      else (ref,))]
    net_sym, params = net.to_sym(
        input_shapes=[tuple(x.shape)], input_dtypes=input_dtypes)
    model = export_to_model_dict(net_sym, params)
    sym2, ap, xp = import_from_model_dict(model)
    env = {k: mxnp.array(v) for k, v in {**ap, **xp}.items()}
    outs = sym2.eval(data=x, **env)
    for got, want in zip(outs, ref_list):
        onp.testing.assert_allclose(got.asnumpy(), want, rtol=rtol,
                                    atol=atol)
    return model


_ZOO_FAST = ["resnet18_v1", "squeezenet1_0", "mobilenet_v2_0_25"]
_ZOO_SLOW = ["alexnet", "vgg11", "vgg11_bn", "resnet18_v2", "densenet121",
             "inception_v3", "mobilenet0_25"]


def _run_zoo_roundtrip(family):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision as zoo
    mx.random.seed(0)
    net = getattr(zoo, family)(classes=10)
    net.initialize(mx.init.Xavier())
    shape = (1, 3, 299, 299) if "inception" in family else (1, 3, 224, 224)
    _roundtrip_net(net, mxnp.random.uniform(size=shape))


@pytest.mark.parametrize("family", _ZOO_FAST)
def test_zoo_family_onnx_roundtrip(family):
    _run_zoo_roundtrip(family)


@pytest.mark.slow
@pytest.mark.parametrize("family", _ZOO_SLOW)
def test_zoo_family_onnx_roundtrip_slow(family):
    _run_zoo_roundtrip(family)


def test_bert_tiny_onnx_roundtrip():
    """bert_tiny exports through the flash-attention decomposition
    (MatMul/Softmax/MatMul), embedding Gather, LayerNormalization, Split
    and Slice — and reimports with matching numerics for both heads."""
    import mxnet_tpu as mx
    from mxnet_tpu.models.bert import bert_tiny
    mx.random.seed(0)
    net = bert_tiny()
    net.initialize(mx.init.Xavier())
    tok = mxnp.array(onp.random.RandomState(0).randint(
        0, 1000, (2, 16)).astype("int32"))
    model = _roundtrip_net(net, tok, rtol=5e-3, atol=5e-3,
                           input_dtypes=["int32"])
    ops = {n["op_type"] for n in model["graph"]["node"]}
    assert {"MatMul", "Softmax", "Gather", "LayerNormalization",
            "Split", "Slice"} <= ops


def test_symbol_getitem_slicing_roundtrip():
    x = sym.var("x", shape=(4, 6), dtype="float32")
    out = x[1:3, 0] * 2.0
    xv = onp.random.RandomState(0).randn(4, 6).astype("float32")
    (ref,) = out.eval(x=mxnp.array(xv))
    onp.testing.assert_allclose(ref.asnumpy(), xv[1:3, 0] * 2, rtol=1e-6)
    model = export_to_model_dict(out, {})
    sym2, _ap, _xp = import_from_model_dict(model)
    (got,) = sym2.eval(x=mxnp.array(xv))
    onp.testing.assert_allclose(got.asnumpy(), ref.asnumpy(), rtol=1e-6)


def test_breadth_ops_roundtrip():
    """Round-4 importer breadth: comparison/logical/reduction/shape ops
    export and reimport with matching numerics."""
    rng = onp.random.RandomState(7)
    av = rng.randn(3, 4).astype("float32")
    bv = rng.randn(3, 4).astype("float32")

    a = sym.var("a", shape=(3, 4), dtype="float32")
    b = sym.var("b", shape=(3, 4), dtype="float32")

    cases = [
        sym.where(a > b, a, b),
        sym.logical_and(a > 0.0, b > 0.0),
        sym.logical_not(a > 0.0),
        sym.maximum(a, b) + sym.minimum(a, b),
        sym.max(a, axis=1, keepdims=True) * 1.0,
        sym.min(a, axis=1) + sym.prod(sym.sigmoid(a), axis=1),
        sym.round(a) + sym.reciprocal(b * b + 1.0),
        sym.tan(a * 0.1) + sym.sinh(a * 0.1) + sym.cosh(b * 0.1),
        sym.arcsin(sym.clip(a, -0.9, 0.9)) + sym.arctan(b),
        sym.cumsum(a, axis=1),
        sym.tile(a, (2, 1)),
        sym.negative(a) + sym.exp(b * 0.1),
    ]
    for i, out in enumerate(cases):
        model = export_to_model_dict(out, {})
        sym2, ap, _xp = import_from_model_dict(model)
        env = {"a": mxnp.array(av), "b": mxnp.array(bv)}
        (ref,) = out.eval(**env)
        env.update({k: mxnp.array(v) for k, v in ap.items()})
        (got,) = sym2.eval(**env)
        onp.testing.assert_allclose(got.asnumpy().astype("float32"),
                                    ref.asnumpy().astype("float32"),
                                    rtol=1e-4, atol=1e-5,
                                    err_msg="case %d" % i)


def test_breadth_legacy_ops_roundtrip():
    x = sym.var("x", shape=(2, 4, 6, 6), dtype="float32")
    rng = onp.random.RandomState(8)
    xv = rng.randn(2, 4, 6, 6).astype("float32")

    # InstanceNorm
    g = sym.var("g", shape=(4,), dtype="float32")
    bta = sym.var("bt", shape=(4,), dtype="float32")
    out = sym.InstanceNorm(x, g, bta, eps=1e-5)
    params = {"g": onp.ones(4, "float32"), "bt": onp.zeros(4, "float32")}
    model = export_to_model_dict(out, params)
    sym2, ap, _xp = import_from_model_dict(model)
    (ref,) = out.eval(x=mxnp.array(xv),
                      **{k: mxnp.array(v) for k, v in params.items()})
    (got,) = sym2.eval(x=mxnp.array(xv),
                       **{k: mxnp.array(v) for k, v in ap.items()})
    onp.testing.assert_allclose(got.asnumpy(), ref.asnumpy(), rtol=1e-4,
                                atol=1e-5)

    # L2Normalization channel mode
    out = sym.L2Normalization(x, mode="channel")
    model = export_to_model_dict(out, {})
    sym2, _ap, _xp = import_from_model_dict(model)
    (ref,) = out.eval(x=mxnp.array(xv))
    (got,) = sym2.eval(x=mxnp.array(xv))
    onp.testing.assert_allclose(got.asnumpy(), ref.asnumpy(), rtol=1e-4)

    # Pad (constant)
    out = sym.Pad(x, mode="constant",
                  pad_width=(0, 0, 0, 0, 1, 1, 2, 2))
    model = export_to_model_dict(out, {})
    sym2, _ap, _xp = import_from_model_dict(model)
    (ref,) = out.eval(x=mxnp.array(xv))
    (got,) = sym2.eval(x=mxnp.array(xv))
    onp.testing.assert_allclose(got.asnumpy(), ref.asnumpy(), rtol=1e-5)

    # UpSampling nearest
    out = sym.UpSampling(x, scale=2, sample_type="nearest")
    model = export_to_model_dict(out, {})
    sym2, _ap, _xp = import_from_model_dict(model)
    (ref,) = out.eval(x=mxnp.array(xv))
    (got,) = sym2.eval(x=mxnp.array(xv))
    onp.testing.assert_allclose(got.asnumpy(), ref.asnumpy(), rtol=1e-5)
