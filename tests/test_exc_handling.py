"""Async exception propagation (reference:
tests/python/unittest/test_exc_handling.py — exceptions inside engine
closures surface at sync points, not at op-issue time)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np as mxnp, autograd
from mxnet_tpu import engine as eng
from mxnet_tpu.gluon import nn


def test_nan_inf_propagate_through_async_chain():
    """Invalid math doesn't raise mid-chain; values surface at fetch
    (XLA semantics — the analog of the reference's deferred error
    surfacing at WaitToRead)."""
    a = mxnp.array([1.0, -1.0])
    out = mxnp.log(a)  # -1 → nan, async
    out2 = out * 2 + 1
    v = out2.asnumpy()  # sync point
    assert onp.isnan(v[1])
    assert onp.isfinite(v[0])


def test_host_engine_exception_at_sync_point():
    e = eng.Engine()
    v = e.new_variable()

    def bad():
        raise ValueError("async boom")

    # push succeeds (async); the exception surfaces at the sync point
    e.push(bad, mutable_vars=[v])
    with pytest.raises(eng.EngineError, match="async boom"):
        e.wait_for_var(v)


def test_exception_in_hybridized_forward_surfaces():
    class Bad(nn.HybridBlock):
        def forward(self, x):
            raise RuntimeError("forward exploded")

    b = Bad()
    b.hybridize()
    with pytest.raises(RuntimeError, match="forward exploded"):
        b(mxnp.zeros(3))


def test_shape_error_raises_eagerly():
    # shape mismatches are host-side metadata → immediate error (the
    # reference also fails these at op-issue time in SetShapeType)
    a = mxnp.zeros((2, 3))
    b = mxnp.zeros((4, 5))
    with pytest.raises(Exception):
        mxnp.dot(a, b).asnumpy()


def test_autograd_backward_outside_record_raises():
    x = mxnp.array([1.0])
    x.attach_grad()
    y = x * 2  # not recorded
    with pytest.raises(Exception):
        y.backward()


def test_waitall_after_failure_does_not_deadlock():
    e = eng.default_engine()
    v = e.new_variable()
    e.push(lambda: (_ for _ in ()).throw(RuntimeError("x")),
           mutable_vars=[v])
    mx.waitall()  # must not hang or raise unrelated errors
    with pytest.raises(eng.EngineError):
        e.wait_for_var(v)
    # recovery: a new write clears the poison
    e.push(lambda: None, mutable_vars=[v])
    e.wait_for_var(v)
