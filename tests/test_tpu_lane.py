"""On-chip test lane (`python -m pytest -m tpu`).

Runs against the real TPU backend when one is present; every test skips
with a reason on CPU.  This is the backend-consistency half of the
reference's test strategy (SURVEY §4: the reference runs the same op suite
against CPU and GPU backends); here the pairs are (XLA reference path,
Pallas kernel) and (f32, bf16) on the actual chip.

What round-2's audit proved this lane is for: a Pallas kernel can compile
in CPU interpret mode yet be unreachable or broken on the real platform.
These tests fail loudly in that case — `test_flash_dispatch_uses_pallas`
asserts the dispatcher took the kernel path (no silent fallback), and the
grad test differentiates through the kernel's custom VJP on-chip.
"""
import numpy as onp
import pytest

pytestmark = pytest.mark.tpu

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

if jax.default_backend() == "cpu":
    pytest.skip("no TPU backend present (CPU only); on-chip lane skipped",
                allow_module_level=True)


def _rand(shape, dtype="float32", seed=0):
    return onp.random.RandomState(seed).randn(*shape).astype(dtype)


def test_flash_kernel_numerics_on_chip():
    from mxnet_tpu.ops.attention import attention_reference
    from mxnet_tpu.ops.pallas.flash_attention import flash_attention_tpu
    B, H, L, D = 2, 4, 512, 64
    q, k, v = (jnp.asarray(_rand((B, H, L, D), seed=s)) for s in range(3))
    for causal, window in [(False, None), (True, None), (True, 64)]:
        out = flash_attention_tpu(q, k, v, causal=causal, window=window)
        ref = attention_reference(q, k, v, causal=causal, window=window)
        # chip matmuls run at default (bf16-pass) precision: loose atol
        onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                    rtol=2e-2, atol=2e-2)


def test_flash_dispatch_uses_pallas():
    from mxnet_tpu.ops import attention
    B, H, L, D = 1, 2, 256, 64
    q, k, v = (jnp.asarray(_rand((B, H, L, D), seed=s)) for s in range(3))
    attention.last_path = None
    attention.flash_attention(q, k, v, causal=True)
    assert attention.last_path == "pallas", (
        f"dispatcher fell back to {attention.last_path!r} on a TPU backend")


def test_flash_grad_through_custom_vjp_on_chip():
    from mxnet_tpu.ops import attention
    from mxnet_tpu.ops.attention import attention_reference
    B, H, L, D = 1, 2, 256, 64
    q, k, v = (jnp.asarray(_rand((B, H, L, D), seed=s)) for s in range(3))

    def loss_fa(q, k, v):
        return (attention.flash_attention(q, k, v, causal=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (attention_reference(q, k, v, causal=True) ** 2).sum()

    attention.last_path = None
    g1 = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
    assert attention.last_path == "pallas"
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=5e-2, atol=5e-2)


def test_flash_long_context_bounded_memory():
    """L=4096 causal attention runs on-chip — the O(L^2) score matrix
    (64 heads x 4096^2 f32 = 4 GiB) would not fit VMEM-resident paths."""
    from mxnet_tpu.ops.pallas.flash_attention import flash_attention_tpu
    B, H, L, D = 2, 8, 4096, 64
    q, k, v = (jnp.asarray(_rand((B, H, L, D), seed=s), dtype=jnp.bfloat16)
               for s in range(3))
    out = flash_attention_tpu(q, k, v, causal=True)
    assert out.shape == (B, H, L, D)
    assert bool(jnp.isfinite(out.astype(jnp.float32)).all())


def test_bf16_parity_dense_on_chip():
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn
    net = nn.HybridSequential()
    net.add(nn.Dense(128, activation="relu"), nn.Dense(16))
    net.initialize()
    net.hybridize()
    x32 = mx.np.array(_rand((8, 64)))
    y32 = net(x32).asnumpy()
    y16 = onp.asarray(
        jnp.asarray(net(x32.astype("bfloat16")).asnumpy()).astype(jnp.float32))
    onp.testing.assert_allclose(y16, y32, rtol=5e-2, atol=5e-2)


def test_donation_on_chip():
    """jit with donate_argnums reuses the input buffer for the output on a
    real device (train-step update pattern: params donated to next params)."""
    @jax.jit
    def probe(x):
        return x + 1.0

    upd = jax.jit(lambda x: x * 2.0, donate_argnums=(0,))
    x = jnp.ones((1024, 1024))
    y = upd(x)
    assert float(y[0, 0]) == 2.0
    assert x.is_deleted()


def test_hybridized_train_step_on_chip():
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(10))
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = mx.np.array(_rand((32, 28)))
    y = mx.np.array(onp.arange(32) % 10)
    losses = []
    for _ in range(5):
        with autograd.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        trainer.step(1)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0], losses


# ---------------------------------------------------------------------------
# round-4 widening (VERDICT r3 #10): flash dropout/kvlen/window sweep,
# int8 MXU, bf16 BatchNorm, bulking dispatch counts, optimizer kernels
# ---------------------------------------------------------------------------
def test_flash_kv_length_on_chip():
    from mxnet_tpu.ops.attention import attention_reference
    from mxnet_tpu.ops.pallas.flash_attention import flash_attention_tpu
    B, H, L, D = 2, 4, 512, 64
    q, k, v = (jnp.asarray(_rand((B, H, L, D), seed=s)) for s in range(3))
    kv = jnp.asarray([200, 512], jnp.int32)
    out = flash_attention_tpu(q, k, v, kv_length=kv)
    ref = attention_reference(q, k, v, kv_length=kv)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=2e-2, atol=2e-2)
    assert bool(jnp.isfinite(out).all())


def test_flash_kv_length_grads_on_chip():
    from mxnet_tpu.ops.attention import attention_reference
    from mxnet_tpu.ops.pallas.flash_attention import flash_attention_tpu
    B, H, L, D = 1, 2, 256, 64
    q, k, v = (jnp.asarray(_rand((B, H, L, D), seed=s)) for s in range(3))
    kv = jnp.asarray([100], jnp.int32)
    g1 = jax.grad(lambda *a: (flash_attention_tpu(
        *a, causal=True, kv_length=kv) ** 2).sum(), (0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: (attention_reference(
        *a, causal=True, kv_length=kv) ** 2).sum(), (0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert bool(jnp.isfinite(a).all())
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=5e-2, atol=5e-2)


def test_flash_dropout_matches_hash_oracle_on_chip():
    from mxnet_tpu.ops.pallas.flash_attention import (flash_attention_tpu,
                                                      hash_keep_bits)
    B, H, L, D = 2, 2, 256, 64
    rate = 0.1
    seed = jnp.asarray([99], jnp.uint32)
    q, k, v = (jnp.asarray(_rand((B, H, L, D), seed=s)) for s in range(3))

    def oracle(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q / onp.sqrt(D), k)
        p = jax.nn.softmax(s, -1)
        gi = jnp.broadcast_to(jnp.arange(L)[:, None], (L, L))
        gj = jnp.broadcast_to(jnp.arange(L)[None, :], (L, L))
        bits = jax.vmap(lambda b: hash_keep_bits(seed[0], b, gi, gj))(
            jnp.arange(B * H))
        thr = jnp.uint32(int(round(rate * 2 ** 32)))
        keep = (bits >= thr).astype(jnp.float32).reshape(B, H, L, L)
        return jnp.einsum("bhqk,bhkd->bhqd", p * keep / (1 - rate), v)

    out = flash_attention_tpu(q, k, v, dropout=rate, seed=seed)
    ref = oracle(q, k, v)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=2e-2, atol=2e-2)


def test_flash_dropout_grads_finite_and_seeded_on_chip():
    from mxnet_tpu.ops.pallas.flash_attention import flash_attention_tpu
    B, H, L, D = 1, 2, 256, 64
    q, k, v = (jnp.asarray(_rand((B, H, L, D), seed=s)) for s in range(3))
    s1 = jnp.asarray([1], jnp.uint32)
    s2 = jnp.asarray([2], jnp.uint32)
    g = jax.grad(lambda *a: (flash_attention_tpu(
        *a, dropout=0.2, seed=s1) ** 2).sum(), (0, 1, 2))(q, k, v)
    for a in g:
        assert bool(jnp.isfinite(a).all())
    # determinism: same seed same output; different seed different mask
    o1 = flash_attention_tpu(q, k, v, dropout=0.2, seed=s1)
    o1b = flash_attention_tpu(q, k, v, dropout=0.2, seed=s1)
    o2 = flash_attention_tpu(q, k, v, dropout=0.2, seed=s2)
    onp.testing.assert_array_equal(onp.asarray(o1), onp.asarray(o1b))
    assert float(jnp.max(jnp.abs(o1 - o2))) > 1e-3


@pytest.mark.parametrize("window", [16, 128])
def test_flash_window_sweep_on_chip(window):
    from mxnet_tpu.ops.attention import attention_reference
    from mxnet_tpu.ops.pallas.flash_attention import flash_attention_tpu
    B, H, L, D = 1, 2, 512, 64
    q, k, v = (jnp.asarray(_rand((B, H, L, D), seed=s)) for s in range(3))
    out = flash_attention_tpu(q, k, v, causal=True, window=window)
    ref = attention_reference(q, k, v, causal=True, window=window)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=2e-2, atol=2e-2)


def test_flash_bf16_on_chip():
    from mxnet_tpu.ops.attention import attention_reference
    from mxnet_tpu.ops.pallas.flash_attention import flash_attention_tpu
    B, H, L, D = 2, 4, 512, 64
    q, k, v = (jnp.asarray(_rand((B, H, L, D), seed=s), jnp.bfloat16)
               for s in range(3))
    out = flash_attention_tpu(q, k, v, causal=True).astype(jnp.float32)
    ref = attention_reference(q, k, v, causal=True).astype(jnp.float32)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=8e-2, atol=8e-2)


def test_int8_mxu_matmul_numerics_on_chip():
    """int8 x int8 -> int32 accumulation on the MXU must be EXACT for
    integer inputs (the quantized-dense core, quantized_fully_connected
    parity)."""
    rng = onp.random.RandomState(0)
    a = rng.randint(-127, 128, (64, 256)).astype(onp.int8)
    b = rng.randint(-127, 128, (128, 256)).astype(onp.int8)
    acc = jax.lax.dot_general(jnp.asarray(a), jnp.asarray(b),
                              (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.int32)
    ref = a.astype(onp.int64) @ b.astype(onp.int64).T
    assert acc.dtype == jnp.int32
    onp.testing.assert_array_equal(onp.asarray(acc), ref.astype(onp.int32))


def test_quantized_dense_on_chip():
    import mxnet_tpu as mx
    from mxnet_tpu.contrib.quantization import QuantizedDense
    from mxnet_tpu.gluon import nn
    mx.random.seed(0)
    dense = nn.Dense(32, in_units=64)
    dense.initialize()
    x = mx.np.array(_rand((8, 64)) * 0.5)
    ref = dense(x).asnumpy()
    q = QuantizedDense(dense, float(x.min().asnumpy()),
                       float(x.max().asnumpy()))
    got = q(x).asnumpy()
    # int8 quantization error bound, not numerical noise
    assert onp.abs(got - ref).max() < 0.1
    assert onp.corrcoef(got.ravel(), ref.ravel())[0, 1] > 0.999


def test_bf16_batchnorm_on_chip():
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon import nn
    mx.random.seed(0)
    bn32 = nn.BatchNorm(in_channels=16)
    bn32.initialize()
    x = mx.np.array(_rand((8, 16, 6, 6)))
    with autograd.record(train_mode=True):
        y32 = bn32(x)
    bn16 = nn.BatchNorm(in_channels=16)
    bn16.initialize()
    bn16.cast("bfloat16")
    with autograd.record(train_mode=True):
        y16 = bn16(x.astype("bfloat16"))
    onp.testing.assert_allclose(
        onp.asarray(jnp.asarray(y16.asnumpy()).astype(jnp.float32)),
        y32.asnumpy(), rtol=5e-2, atol=5e-2)
    # running stats updated in both dtypes
    assert float(onp.abs(bn32.running_var.data().asnumpy() - 1).max()) > 1e-4
    assert float(onp.abs(bn16.running_var.data().asnumpy()
                         .astype(onp.float32) - 1).max()) > 1e-4


def test_bulking_steady_state_dispatch_counts_on_chip():
    """The eager-bulking contract on the real chip: after warmup, an
    imperative train step costs a handful of flushes and ZERO compiles
    (VERDICT r3 weak #8: the bulking path had no on-chip assertions)."""
    import mxnet_tpu as mx
    from mxnet_tpu import _bulk, autograd, gluon
    from mxnet_tpu.gluon import nn
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(10))
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "aggregate_num": 100})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = mx.np.array(_rand((16, 32)))
    y = mx.np.array(onp.arange(16) % 10)

    def step():
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(16)
        return loss

    for _ in range(4):
        loss = step()
    float(loss.mean())
    s0 = _bulk.stats()
    for _ in range(3):
        loss = step()
    float(loss.mean())
    s1 = _bulk.stats()
    assert s1["compiles"] - s0["compiles"] == 0, "steady state recompiled"
    assert s1["eager_fallbacks"] - s0["eager_fallbacks"] == 0
    assert (s1["flushes"] - s0["flushes"]) <= 12  # a handful per step


def test_deferred_vjp_backward_matches_jax_grad_on_chip():
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    xv = _rand((8, 8), seed=3)
    x = mx.np.array(xv)
    x.attach_grad()
    with autograd.record():
        loss = ((x @ x).tanh() ** 2).sum()
    loss.backward()
    ref = jax.grad(lambda a: (jnp.tanh(a @ a) ** 2).sum())(jnp.asarray(xv))
    onp.testing.assert_allclose(x.grad.asnumpy(), onp.asarray(ref),
                                rtol=2e-2, atol=2e-2)


def test_fused_multi_sgd_on_chip():
    from mxnet_tpu.ops.optimizer_ops import multi_sgd_mom_update
    ws = [jnp.asarray(_rand((64, 64), seed=i)) for i in range(4)]
    gs = [jnp.asarray(_rand((64, 64), seed=10 + i)) for i in range(4)]
    ms = [jnp.zeros((64, 64)) for _ in range(4)]
    out = multi_sgd_mom_update(ws, gs, ms, lrs=[0.1] * 4, momentum=0.9,
                               wds=[0.0] * 4)
    new_ws = out[0] if isinstance(out, tuple) else out
    for w0, g, w1 in zip(ws, gs, new_ws):
        onp.testing.assert_allclose(onp.asarray(w1),
                                    onp.asarray(w0) - 0.1 * onp.asarray(g),
                                    rtol=2e-2, atol=2e-4)


def test_adam_update_on_chip():
    from mxnet_tpu.ops.optimizer_ops import adam_update
    w = jnp.asarray(_rand((128,), seed=0))
    g = jnp.asarray(_rand((128,), seed=1))
    mean = jnp.zeros(128)
    var = jnp.zeros(128)
    out = adam_update(w, g, mean, var, lr=1e-3)
    w1 = out[0] if isinstance(out, (tuple, list)) else out
    assert bool(jnp.isfinite(w1).all())
    assert float(jnp.max(jnp.abs(w1 - w))) > 0  # moved


def test_lstm_fused_scan_on_chip():
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import rnn
    mx.random.seed(0)
    lstm = rnn.LSTM(32, num_layers=2, layout="NTC", input_size=16)
    lstm.initialize()
    x = mx.np.array(_rand((4, 12, 16)))
    out = lstm(x)
    assert out.shape == (4, 12, 32)
    assert onp.isfinite(out.asnumpy()).all()


def test_all_finite_on_chip():
    from mxnet_tpu import npx
    import mxnet_tpu as mx
    good = mx.np.array(_rand((64,)))
    bad = mx.np.array(onp.array([1.0, onp.inf], onp.float32))
    assert bool(npx.all_finite(good).asnumpy())
    assert not bool(npx.all_finite(good, bad).asnumpy())


def test_embedding_take_on_chip():
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn
    mx.random.seed(0)
    emb = nn.Embedding(100, 16)
    emb.initialize()
    tok = mx.np.array(onp.array([[1, 5, 99], [0, 2, 3]], onp.int32))
    out = emb(tok)
    w = emb.weight.data().asnumpy()
    onp.testing.assert_allclose(out.asnumpy(), w[tok.asnumpy()], rtol=1e-6)


def test_large_reduction_f32_accuracy_on_chip():
    """Big f32 sum must accumulate in f32 (not bf16) on the chip."""
    x = jnp.full((1 << 20,), 1.0e-3, jnp.float32)
    s = float(jnp.sum(x))
    assert abs(s - 1048.576) / 1048.576 < 1e-3, s


def test_device_memory_census_on_chip():
    from mxnet_tpu import profiler
    st0 = profiler.device_memory_stats()
    big = jnp.ones((2048, 2048), jnp.float32)  # 16 MB
    jax.block_until_ready(big)
    st1 = profiler.device_memory_stats()
    assert st1["bytes_in_use"] >= st0["bytes_in_use"] + (8 << 20)
    spec = profiler.chip_spec()
    assert spec["hbm_bytes"] and spec["peak_flops_bf16"]
    del big
