"""On-chip test lane (`python -m pytest -m tpu`).

Runs against the real TPU backend when one is present; every test skips
with a reason on CPU.  This is the backend-consistency half of the
reference's test strategy (SURVEY §4: the reference runs the same op suite
against CPU and GPU backends); here the pairs are (XLA reference path,
Pallas kernel) and (f32, bf16) on the actual chip.

What round-2's audit proved this lane is for: a Pallas kernel can compile
in CPU interpret mode yet be unreachable or broken on the real platform.
These tests fail loudly in that case — `test_flash_dispatch_uses_pallas`
asserts the dispatcher took the kernel path (no silent fallback), and the
grad test differentiates through the kernel's custom VJP on-chip.
"""
import numpy as onp
import pytest

pytestmark = pytest.mark.tpu

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

if jax.default_backend() == "cpu":
    pytest.skip("no TPU backend present (CPU only); on-chip lane skipped",
                allow_module_level=True)


def _rand(shape, dtype="float32", seed=0):
    return onp.random.RandomState(seed).randn(*shape).astype(dtype)


def test_flash_kernel_numerics_on_chip():
    from mxnet_tpu.ops.attention import attention_reference
    from mxnet_tpu.ops.pallas.flash_attention import flash_attention_tpu
    B, H, L, D = 2, 4, 512, 64
    q, k, v = (jnp.asarray(_rand((B, H, L, D), seed=s)) for s in range(3))
    for causal, window in [(False, None), (True, None), (True, 64)]:
        out = flash_attention_tpu(q, k, v, causal=causal, window=window)
        ref = attention_reference(q, k, v, causal=causal, window=window)
        # chip matmuls run at default (bf16-pass) precision: loose atol
        onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                    rtol=2e-2, atol=2e-2)


def test_flash_dispatch_uses_pallas():
    from mxnet_tpu.ops import attention
    B, H, L, D = 1, 2, 256, 64
    q, k, v = (jnp.asarray(_rand((B, H, L, D), seed=s)) for s in range(3))
    attention.last_path = None
    attention.flash_attention(q, k, v, causal=True)
    assert attention.last_path == "pallas", (
        f"dispatcher fell back to {attention.last_path!r} on a TPU backend")


def test_flash_grad_through_custom_vjp_on_chip():
    from mxnet_tpu.ops import attention
    from mxnet_tpu.ops.attention import attention_reference
    B, H, L, D = 1, 2, 256, 64
    q, k, v = (jnp.asarray(_rand((B, H, L, D), seed=s)) for s in range(3))

    def loss_fa(q, k, v):
        return (attention.flash_attention(q, k, v, causal=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (attention_reference(q, k, v, causal=True) ** 2).sum()

    attention.last_path = None
    g1 = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
    assert attention.last_path == "pallas"
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=5e-2, atol=5e-2)


def test_flash_long_context_bounded_memory():
    """L=4096 causal attention runs on-chip — the O(L^2) score matrix
    (64 heads x 4096^2 f32 = 4 GiB) would not fit VMEM-resident paths."""
    from mxnet_tpu.ops.pallas.flash_attention import flash_attention_tpu
    B, H, L, D = 2, 8, 4096, 64
    q, k, v = (jnp.asarray(_rand((B, H, L, D), seed=s), dtype=jnp.bfloat16)
               for s in range(3))
    out = flash_attention_tpu(q, k, v, causal=True)
    assert out.shape == (B, H, L, D)
    assert bool(jnp.isfinite(out.astype(jnp.float32)).all())


def test_bf16_parity_dense_on_chip():
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn
    net = nn.HybridSequential()
    net.add(nn.Dense(128, activation="relu"), nn.Dense(16))
    net.initialize()
    net.hybridize()
    x32 = mx.np.array(_rand((8, 64)))
    y32 = net(x32).asnumpy()
    y16 = onp.asarray(
        jnp.asarray(net(x32.astype("bfloat16")).asnumpy()).astype(jnp.float32))
    onp.testing.assert_allclose(y16, y32, rtol=5e-2, atol=5e-2)


def test_donation_on_chip():
    """jit with donate_argnums reuses the input buffer for the output on a
    real device (train-step update pattern: params donated to next params)."""
    @jax.jit
    def probe(x):
        return x + 1.0

    upd = jax.jit(lambda x: x * 2.0, donate_argnums=(0,))
    x = jnp.ones((1024, 1024))
    y = upd(x)
    assert float(y[0, 0]) == 2.0
    assert x.is_deleted()


def test_hybridized_train_step_on_chip():
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(10))
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = mx.np.array(_rand((32, 28)))
    y = mx.np.array(onp.arange(32) % 10)
    losses = []
    for _ in range(5):
        with autograd.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        trainer.step(1)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0], losses
