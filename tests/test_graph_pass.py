"""Graph-pass registry over the sym DAG (reference nnvm pass registry +
custom pass seam, include/nnvm/pass.h / example/extensions/lib_pass)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np as mxnp
from mxnet_tpu import sym_api as sym
from mxnet_tpu import graph_pass


def _ops(s):
    return [n for n in s._topo() if n._kind == "op"]


def test_fold_constants():
    x = sym.var("x")
    c = sym.add(sym.Symbol("const", attrs={"value": 2.0}),
                sym.Symbol("const", attrs={"value": 3.0}))  # 2+3
    out = sym.multiply(x, c)
    folded = graph_pass.apply_pass(out, "fold-constants")
    kinds = [n._kind for n in folded._topo()]
    assert kinds.count("op") == 1  # only the multiply remains
    (ref,) = out.eval(x=mxnp.array([1.0, 2.0]))
    (got,) = folded.eval(x=mxnp.array([1.0, 2.0]))
    onp.testing.assert_allclose(got.asnumpy(), ref.asnumpy(), rtol=1e-6)


def test_eliminate_common_expr():
    x = sym.var("x")
    a = sym.sin(x)
    b = sym.sin(x)  # structurally identical
    out = sym.add(a, b)
    cse = graph_pass.apply_pass(out, "eliminate-common-expr")
    assert len(_ops(out)) == 3
    assert len(_ops(cse)) == 2  # one sin + one add
    v = mxnp.array([0.3, 0.6])
    onp.testing.assert_allclose(cse.eval(x=v)[0].asnumpy(),
                                out.eval(x=v)[0].asnumpy(), rtol=1e-6)


def test_dead_node_elimination_drops_unreachable():
    x = sym.var("x")
    live = sym.sin(x)
    _dead = sym.exp(live)  # never consumed by the head
    out = sym.multiply(live, 2.0)
    pruned = graph_pass.apply_pass(out, "dead-node-elimination")
    assert all(n._op != "np:exp" for n in _ops(pruned))
    v = mxnp.array([0.1])
    onp.testing.assert_allclose(pruned.eval(x=v)[0].asnumpy(),
                                out.eval(x=v)[0].asnumpy(), rtol=1e-6)


def test_custom_pass_registration_and_rewrite_seam():
    @graph_pass.register("swap-sin-for-cos")
    def swap(s):
        def xform(node, new_inputs):
            if node._kind == "op" and node._op == "np:sin":
                return sym.Symbol("op", op="np:cos", inputs=new_inputs,
                                  name=node.name)
            return None
        return graph_pass.rewrite(s, xform)

    x = sym.var("x")
    out = graph_pass.apply_pass(sym.sin(x), "swap-sin-for-cos")
    (got,) = out.eval(x=mxnp.array([0.5]))
    onp.testing.assert_allclose(got.asnumpy(), onp.cos([0.5]), rtol=1e-6)
    assert "swap-sin-for-cos" in graph_pass.list_passes()


def test_apply_passes_chain_and_unknown_pass():
    x = sym.var("x")
    out = sym.add(sym.sin(x), sym.sin(x))
    r = graph_pass.apply_passes(out, ["eliminate-common-expr",
                                      "dead-node-elimination"])
    assert len(_ops(r)) == 2
    with pytest.raises(ValueError, match="unknown graph pass"):
        graph_pass.apply_pass(out, "nope")
