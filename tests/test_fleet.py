"""Serving-fleet tier-1 matrix (in-process replicas unless a real
process is the point) plus the slow chaos acceptance.

Covers: least-loaded and consistent-hash dispatch, strike/eject/
re-admit passive+active failure detection, shed-retry then router-level
shed (backpressure propagation with Retry-After), deterministic
router.dispatch fault injection, idempotency-aware failover, rolling
rollout with canary abort + rollback (zero-downtime under concurrent
traffic), persistent-compile-cache warm restart, and the supervisor's
auto-restart + crash-loop budget.  The SIGKILL-a-replica-under-
sustained-load acceptance runs tools/chaos.py --scenario fleet in the
slow lane.
"""
import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as onp
import pytest

from mxnet_tpu import faults, profiler, serving
from mxnet_tpu.serving.fleet import rollout
from mxnet_tpu.serving.replica import demo_affine

pytestmark = [pytest.mark.serving, pytest.mark.fleet]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ITEM = (4,)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _server(fn=None, *, admin=True, max_queue_depth=256, flush_ms=2,
            **load_kwargs):
    """One in-process 'replica': registry + batcher + HTTP server."""
    reg = serving.ModelRegistry()
    reg.load("m", fn if fn is not None else demo_affine(scale=2.0),
             item_shape=ITEM, max_batch_size=4, warmup=False,
             **load_kwargs)
    srv = serving.ModelServer(reg, flush_ms=flush_ms, admin=admin,
                              max_queue_depth=max_queue_depth)
    srv.start()
    return srv


def _addrs(servers):
    return ["127.0.0.1:%d" % s.port for s in servers]


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


X = onp.arange(8, dtype="float32").reshape(2, 4)


# ---------------------------------------------------------------------------
# dispatch policies
# ---------------------------------------------------------------------------
def test_least_loaded_dispatch_spreads_and_is_correct():
    servers = [_server() for _ in range(3)]
    router = serving.Router(_addrs(servers), probe_ms=0)
    rs = serving.RouterServer(router)
    rs.start()
    try:
        cli = serving.ServingClient(*rs.address, timeout=10)
        for _ in range(12):
            onp.testing.assert_allclose(cli.predict("m", X), X * 2.0)
        st = router.states()
        # every replica took traffic (round-robin tie-break on idle)
        assert all(s["counters"]["responses"] > 0 for s in st.values()), st
        snap = router.snapshot()
        assert snap["counters"]["responses_total"] == 12
        assert "p99_ms" in snap["latency"]
        # the fleet profiler table recorded the dispatches
        assert profiler.aggregate_stats()["fleet"][
            "router.dispatch"]["count"] >= 12
        cli.close()
    finally:
        rs.stop()
        for s in servers:
            s.stop()


def test_consistent_hash_affinity_and_remap_on_ejection():
    servers = [_server() for _ in range(3)]
    router = serving.Router(_addrs(servers), policy="hash", probe_ms=0)
    try:
        per_key_owner = {}
        for key in range(40):
            before = {rid: s["counters"]["dispatched"]
                      for rid, s in router.states().items()}
            status, _ = router.dispatch(
                "/v1/models/m:predict", {"instances": [X[0].tolist()]},
                affinity_key="k%d" % key)
            assert status == 200
            after = {rid: s["counters"]["dispatched"]
                     for rid, s in router.states().items()}
            owner = [rid for rid in after if after[rid] > before[rid]]
            assert len(owner) == 1
            per_key_owner["k%d" % key] = owner[0]
        # 40 keys spread over >1 replica (vnode ring, not mod-hash)
        assert len(set(per_key_owner.values())) > 1
        # repeating any key hits the same owner
        for key, owner in list(per_key_owner.items())[:5]:
            before = router.states()[owner]["counters"]["dispatched"]
            router.dispatch("/v1/models/m:predict",
                            {"instances": [X[0].tolist()]},
                            affinity_key=key)
            assert router.states()[owner]["counters"]["dispatched"] \
                == before + 1
        # eject an owner: only ITS keys remap, and deterministically
        victim = per_key_owner["k0"]
        with router._lock:
            router._replicas[victim].state = "ejected"
        status, _ = router.dispatch("/v1/models/m:predict",
                                    {"instances": [X[0].tolist()]},
                                    affinity_key="k0")
        assert status == 200  # served by the next ring owner
        # re-admit: the key returns home (ring is stable, not rebuilt)
        with router._lock:
            router._replicas[victim].state = "healthy"
        before = router.states()[victim]["counters"]["dispatched"]
        router.dispatch("/v1/models/m:predict",
                        {"instances": [X[0].tolist()]},
                        affinity_key="k0")
        assert router.states()[victim]["counters"]["dispatched"] \
            == before + 1
    finally:
        router.stop()
        for s in servers:
            s.stop()


# ---------------------------------------------------------------------------
# failure detection: strikes, ejection, re-admission
# ---------------------------------------------------------------------------
def test_strike_eject_readmit_cycle():
    live = _server()
    dead_port = _free_port()  # nothing listening: connect refused
    router = serving.Router(
        ["127.0.0.1:%d" % dead_port, "127.0.0.1:%d" % live.port],
        strikes=2, probe_ms=50, eject_backoff_ms=50)
    dead_rid = "127.0.0.1:%d" % dead_port
    try:
        # every request succeeds (failover), while the dead replica
        # accumulates strikes and gets ejected
        for _ in range(6):
            status, doc = router.dispatch("/v1/models/m:predict",
                                          {"instances": [X[0].tolist()]})
            assert status == 200
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                router.states()[dead_rid]["state"] != "ejected":
            time.sleep(0.02)
        st = router.states()[dead_rid]
        assert st["state"] == "ejected"
        assert st["counters"]["ejections"] >= 1
        assert router.metrics.counters["retries_total"] >= 1
        # traffic now bypasses the ejected replica entirely
        before = router.states()[dead_rid]["counters"]["dispatched"]
        for _ in range(4):
            assert router.dispatch("/v1/models/m:predict",
                                   {"instances": [X[0].tolist()]}
                                   )[0] == 200
        assert router.states()[dead_rid]["counters"]["dispatched"] \
            == before
        # a server appears on the dead port: probe loop re-admits it
        reg = serving.ModelRegistry()
        reg.load("m", demo_affine(scale=2.0), item_shape=ITEM,
                 max_batch_size=4, warmup=False)
        revived = serving.ModelServer(reg, flush_ms=2, port=dead_port)
        revived.start()
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and \
                    router.states()[dead_rid]["state"] != "healthy":
                time.sleep(0.05)
            st = router.states()[dead_rid]
            assert st["state"] == "healthy", st
            assert st["counters"]["readmissions"] >= 1
            ev = profiler.aggregate_stats()["events"]
            assert ev.get("fleet.eject", 0) >= 1
            assert ev.get("fleet.readmit", 0) >= 1
        finally:
            revived.stop()
    finally:
        router.stop()
        live.stop()


def test_router_dispatch_fault_injection_fails_over():
    """Deterministic chaos at the router.dispatch site: injected resets
    read as replica transport failures (strike + failover) yet every
    client request still succeeds."""
    servers = [_server() for _ in range(2)]
    router = serving.Router(_addrs(servers), strikes=5, probe_ms=0)
    try:
        with faults.inject("router.dispatch", "reset", n=3):
            for _ in range(9):
                status, _ = router.dispatch(
                    "/v1/models/m:predict", {"instances": [X[0].tolist()]})
                assert status == 200
        # >= 3: the failover retries re-enter the injection site, so a
        # retry can itself trip the every-3rd-call rule
        assert faults.stats()["tripped"]["router.dispatch"] >= 3
        assert router.metrics.counters["retries_total"] >= 3
        assert router.metrics.counters["responses_total"] == 9
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_non_idempotent_inflight_failure_not_replayed():
    """A connection the replica kills AFTER reading the request fails
    over only for idempotent requests; ``idempotent=False`` surfaces the
    failure instead of double-running the predict."""
    # slammer replica: accepts, reads, slams — reply-phase loss
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(8)
    hits = []
    stop = threading.Event()

    def slammer():
        lsock.settimeout(0.2)
        while not stop.is_set():
            try:
                conn, _ = lsock.accept()
            except socket.timeout:
                continue
            hits.append(1)
            try:
                conn.recv(65536)
            finally:
                conn.close()

    t = threading.Thread(target=slammer, daemon=True)
    t.start()
    good = _server()
    # slammer first: least-loaded tie-break picks insertion order on idle
    router = serving.Router(
        ["127.0.0.1:%d" % lsock.getsockname()[1],
         "127.0.0.1:%d" % good.port], strikes=10, probe_ms=0)
    try:
        n0 = len(hits)
        with pytest.raises(serving.ServingError, match="non-idempotent"):
            router.dispatch("/v1/models/m:predict",
                            {"instances": [X[0].tolist()]},
                            idempotent=False)
        assert len(hits) - n0 == 1  # sent once, reply lost, NOT replayed
        # same failure with the default (stateless models are pure):
        # fails over to the good replica and succeeds
        status, doc = router.dispatch("/v1/models/m:predict",
                                      {"instances": [X[0].tolist()]})
        assert status == 200
    finally:
        router.stop()
        good.stop()
        stop.set()
        t.join(5)
        lsock.close()


def test_poisoned_request_error_propagates_not_shed():
    """A request that fails the MODEL on every replica (poisoned input)
    must come back as the replica's own 500, not disguise itself as a
    503 fleet-overload shed — it would fail everywhere forever."""
    def fussy(batch):
        if onp.isnan(onp.asarray(batch)).any():
            raise ValueError("poisoned input")
        return onp.asarray(batch) * 2.0

    servers = [_server(fussy) for _ in range(2)]
    router = serving.Router(_addrs(servers), strikes=10, probe_ms=0)
    try:
        poison = [1.0, float("nan"), 1.0, 1.0]
        status, doc = router.dispatch("/v1/models/m:predict",
                                      {"instances": [poison]})
        assert status == 500 and "poisoned" in doc["error"]
        # both replicas were tried (the retry), then the error surfaced
        assert sum(s["counters"]["errors"]
                   for s in router.states().values()) == 2
        # the fleet still serves good requests
        status, _ = router.dispatch("/v1/models/m:predict",
                                    {"instances": [X[0].tolist()]})
        assert status == 200
    finally:
        router.stop()
        for s in servers:
            s.stop()


# ---------------------------------------------------------------------------
# backpressure propagation
# ---------------------------------------------------------------------------
def test_shed_retry_then_router_shed_with_retry_after():
    """Replica 503 load-shed retries once on the least-loaded
    alternative; when EVERY replica sheds, the router sheds at its own
    socket with Retry-After instead of queueing."""
    gates = [threading.Event(), threading.Event()]

    def blocked(gate):
        def fn(batch):
            gate.wait(20)
            return onp.asarray(batch) * 2.0
        return fn

    servers = [_server(blocked(g), max_queue_depth=1, flush_ms=1)
               for g in gates]
    router = serving.Router(_addrs(servers), probe_ms=0)
    rs = serving.RouterServer(router)
    rs.start()
    try:
        cli = serving.ServingClient(*rs.address, timeout=20, retries=0)
        # occupy both replicas' workers + fill both queues directly
        futs = []
        for srv in servers:
            futs.append(srv.batcher.submit("m", X[0]))  # worker grabs it
            for _ in range(200):
                if srv.batcher.queue_depth("m") == 0:
                    break
                time.sleep(0.005)
            futs.append(srv.batcher.submit("m", X[0]))  # queue now full
        # through the router: replica A sheds -> retried on B -> B sheds
        # -> the ROUTER sheds with Retry-After (backpressure propagated)
        with pytest.raises(serving.QueueFullError) as ei:
            cli.predict("m", X[:1], deadline_ms=5000)
        assert getattr(ei.value, "retry_after", None) is not None
        st = router.states()
        assert sum(s["counters"]["sheds"] for s in st.values()) == 2
        assert router.metrics.counters["shed_total"] >= 3  # 2 + router's
        # relief: open the gates, the fleet serves again (single item:
        # a 2-instance batch could legitimately re-shed a depth-1 queue)
        for g in gates:
            g.set()
        for f in futs:
            f.result(timeout=20)
        onp.testing.assert_allclose(cli.predict("m", X[:1]), X[:1] * 2.0)
        cli.close()
    finally:
        for g in gates:
            g.set()
        rs.stop()
        for s in servers:
            s.stop()


# ---------------------------------------------------------------------------
# rolling rollout
# ---------------------------------------------------------------------------
def test_rolling_rollout_zero_downtime_under_traffic():
    """Rollout drains one replica at a time and hot-swaps via the
    registry: concurrent traffic sees zero failures, old results until
    the flip, new ones after, and BOTH replicas finish on the new
    version."""
    servers = [_server() for _ in range(2)]
    router = serving.Router(_addrs(servers), probe_ms=0)
    errors, stop = [], threading.Event()

    def traffic():
        while not stop.is_set():
            try:
                status, doc = router.dispatch(
                    "/v1/models/m:predict", {"instances": [X[0].tolist()]})
                assert status == 200, doc
                v = float(doc["predictions"][0][0])
                if v not in (0.0,):  # X[0][0] == 0 -> 0 under any scale
                    errors.append(("value", v))
            except Exception as e:  # pragma: no cover
                errors.append(("exc", repr(e)))

    th = threading.Thread(target=traffic, daemon=True)
    th.start()
    try:
        report = rollout(
            router,
            {"name": "m",
             "builder": "mxnet_tpu.serving.replica:demo_affine",
             "kwargs": {"scale": 3.0}, "item_shape": list(ITEM),
             "max_batch_size": 4, "warmup": False}, canary_probes=4)
        stop.set()
        th.join(10)
        assert not errors, errors[:3]
        assert report["version"] == 2 and not report["aborted"]
        assert report["canary"]["errors"] == 0
        for srv in servers:
            assert srv.registry.latest_version("m") == 2
        status, doc = router.dispatch("/v1/models/m:predict",
                                      {"instances": [X[1].tolist()]})
        onp.testing.assert_allclose(onp.asarray(doc["predictions"][0]),
                                    X[1] * 3.0)
        # nobody is left drained
        assert not any(s["draining"] for s in router.states().values())
    finally:
        stop.set()
        router.stop()
        for s in servers:
            s.stop()


def test_rollout_canary_abort_rolls_back():
    """A new version whose canary error rate regresses is unloaded
    everywhere it landed; the fleet converges back to the old version
    and replicas 2..N never see the bad version at all."""
    servers = [_server() for _ in range(3)]
    router = serving.Router(_addrs(servers), probe_ms=0)
    try:
        with pytest.raises(serving.RolloutAbortedError, match="error rate"):
            rollout(router,
                    {"name": "m",
                     "builder": "mxnet_tpu.serving.replica:demo_faulty",
                     "kwargs": {"p": 1.0}, "item_shape": list(ITEM),
                     "max_batch_size": 4, "warmup": False},
                    canary_probes=4)
        ev = profiler.aggregate_stats()["events"]
        assert ev.get("fleet.rollout_abort", 0) >= 1
        for srv in servers:
            assert srv.registry.latest_version("m") == 1  # rolled back
        assert not any(s["draining"] for s in router.states().values())
        status, doc = router.dispatch("/v1/models/m:predict",
                                      {"instances": [X[0].tolist()]})
        assert status == 200  # old version still serving
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_rollout_canary_p99_regression_aborts():
    """The canary gate also trips on tail latency: a new version 50x
    slower than baseline rolls back even though it answers correctly."""
    servers = [_server() for _ in range(2)]
    router = serving.Router(_addrs(servers), probe_ms=0)
    try:
        with pytest.raises(serving.RolloutAbortedError, match="p99"):
            rollout(router,
                    {"name": "m",
                     "builder": "mxnet_tpu.serving.replica:demo_affine",
                     "kwargs": {"scale": 3.0, "slow_ms": 300.0},
                     "item_shape": list(ITEM), "max_batch_size": 4,
                     "warmup": False},
                    canary_probes=3, canary_p99_factor=5.0)
        for srv in servers:
            assert srv.registry.latest_version("m") == 1
    finally:
        router.stop()
        for s in servers:
            s.stop()


# ---------------------------------------------------------------------------
# persistent compile cache (warm restart)
# ---------------------------------------------------------------------------
_CACHE_SCRIPT = r"""
import json, os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["MXNET_COMPILE_CACHE_DIR"] = sys.argv[1]
from mxnet_tpu import serving
from mxnet_tpu.serving.replica import demo_dense
reg = serving.ModelRegistry()   # enables the cache (env knob)
t0 = time.monotonic()
served = reg.load("m", demo_dense(seed=0), item_shape=(16,),
                  max_batch_size=4)  # warmup=True: compile every bucket
print(json.dumps({"warm_s": time.monotonic() - t0,
                  "warmed": served.warmed,
                  "entries": sorted(f for f in os.listdir(sys.argv[1])
                                    if f.endswith("-cache"))}))
"""


def test_compile_cache_warm_restart(tmp_path):
    """Two replica boots against one MXNET_COMPILE_CACHE_DIR: the first
    writes per-bucket executables, the second's warmup is pure cache
    reads — zero NEW cache entries (every compile was a hit)."""
    cache = str(tmp_path / "xla-cache")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    def boot():
        out = subprocess.run([sys.executable, "-c", _CACHE_SCRIPT, cache],
                             capture_output=True, text=True, timeout=300,
                             env=env, cwd=REPO)
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])

    first = boot()
    assert first["warmed"] and first["entries"], first
    second = boot()
    assert second["warmed"]
    # warm restart compiled NOTHING new: same cache entries, all hits
    assert second["entries"] == first["entries"]


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------
class _FakeProc:
    def __init__(self, rc=1):
        self.pid = 4242
        self._rc = rc

    def poll(self):
        return self._rc

    def wait(self, timeout=None):
        return self._rc

    def send_signal(self, sig):
        pass


def test_supervisor_crash_loop_budget_and_backoff(monkeypatch):
    """A replica that dies instantly is restarted with exponential
    backoff at most restart_budget times per window, then declared
    failed — the crash-loop brake (unit-level: fake processes)."""
    sup = serving.ReplicaSupervisor(
        {"models": []}, replicas=1, restart_budget=3,
        restart_window_s=60.0, restart_backoff_ms=300)
    spawns = []

    def fake_spawn(r):
        spawns.append(time.monotonic())
        r.proc = _FakeProc(rc=1)  # dies immediately
        r.state = "running"
        r.started_at = time.monotonic()
        return r

    monkeypatch.setattr(sup, "_spawn", fake_spawn)
    sup._spec_path = None
    fake_spawn(sup.replicas[0])
    sup._monitor = threading.Thread(target=sup._monitor_loop, daemon=True)
    sup._monitor.start()
    try:
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and \
                sup.replicas[0].state != "failed":
            time.sleep(0.02)
        r = sup.replicas[0]
        assert r.state == "failed"
        assert r.restarts == 3  # the budget, not one more
        assert len(spawns) == 4  # initial + 3 restarts
        # consecutive crashes backed off: 0.3/0.6/1.2 s (the monitor's
        # 0.1 s poll quantizes, hence the coarse base + margin)
        gaps = [b - a for a, b in zip(spawns, spawns[1:])]
        assert gaps[-1] > gaps[0] + 0.4
        ev = profiler.aggregate_stats()["events"]
        assert ev.get("fleet.crash_loop", 0) >= 1
    finally:
        sup._stop.set()
        sup._monitor.join(5)


def test_supervisor_restarts_sigkilled_replica_real_process():
    """One REAL replica process: SIGKILL it, the supervisor respawns it
    on the same port and it answers /readyz again (the router re-admits
    by address, so no reconfiguration is ever needed)."""
    spec = {"models": [{"name": "m",
                        "builder": "mxnet_tpu.serving.replica:demo_affine",
                        "kwargs": {"scale": 2.0}, "item_shape": [4],
                        "max_batch_size": 4, "warmup": False}],
            "flush_ms": 2}
    sup = serving.ReplicaSupervisor(
        spec, replicas=1, restart_backoff_ms=50,
        env={"JAX_PLATFORMS": "cpu"})
    try:
        sup.start()
        assert sup.ready_count() == 1
        port = sup.replicas[0].port
        pid0 = sup.replicas[0].proc.pid
        sup.kill(0, signal.SIGKILL)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and sup.ready_count() < 1:
            time.sleep(0.1)
        r = sup.replicas[0]
        assert r.alive() and r.proc.pid != pid0
        assert r.port == port and r.restarts == 1
        # the restarted replica actually serves
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("POST", "/v1/models/m:predict",
                     body=json.dumps({"instances": [[1, 1, 1, 1]]}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        doc = json.loads(resp.read())
        conn.close()
        assert resp.status == 200
        onp.testing.assert_allclose(doc["predictions"][0],
                                    [2.0, 2.0, 2.0, 2.0])
    finally:
        sup.stop()


def test_replica_crash_fault_site_parses():
    rules = faults.parse_spec(
        "replica.crash:kill@n=5;router.dispatch:reset@p=0.1")
    assert [r.site for r in rules] == ["replica.crash", "router.dispatch"]
    with faults.inject("replica.crash", "kill", n=1):
        assert faults.check("replica.crash") == "kill"  # soft kind


# ---------------------------------------------------------------------------
# chaos acceptance (slow lane)
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_chaos_fleet_sigkill_under_load_and_rollout():
    """The ISSUE acceptance: SIGKILL one of 3 replicas mid-traffic —
    zero failed requests, p99 < 5x steady state, supervisor restores
    the fleet, and a rolling rollout completes during traffic."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos.py"),
         "--scenario", "fleet", "-n", "3"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    sys.stdout.write(out.stdout[-3000:])
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    assert "chaos: PASS" in out.stdout
