"""Persistent fused-cell Pallas kernels (ops/pallas/fused_cell):
LSTM fused-vs-scan parity (fwd + grads, fp32/bf16), wavefront
interaction, bidirectional fallback, hybridized end-to-end, trace
signatures, the fused decode step, launch-census gates, and the bounded
decode/prefill program cache.

The CPU lane runs the kernels in Pallas interpreter mode
(MXNET_RNN_FUSED_CELL=interpret / MXNET_DECODE_FUSED=interpret) — the
identical kernel code path the TPU compiles.
"""
import os

import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import np, autograd
from mxnet_tpu.gluon import rnn
from mxnet_tpu.ops import rnn as oprnn
from mxnet_tpu.ops.pallas import fused_cell as fc

pytestmark = pytest.mark.rnn


def _rand_lstm(T, B, I, H, L=1, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.key(seed), 4)
    x = jax.random.normal(ks[0], (T, B, I), jnp.float32).astype(dtype)
    params = (jax.random.normal(
        ks[1], (oprnn.param_size("lstm", I, H, L),), jnp.float32)
        * 0.2).astype(dtype)
    h0 = (jax.random.normal(ks[2], (L, B, H), jnp.float32)
          * 0.3).astype(dtype)
    c0 = (jax.random.normal(ks[3], (L, B, H), jnp.float32)
          * 0.3).astype(dtype)
    return x, params, h0, c0


def _forward(x, params, h0, c0, H, L, fused):
    return oprnn.rnn_forward(x, params, h0, c0, "lstm", H, L, fused=fused)


# ---------------------------------------------------------------------------
# forward + backward parity, fused vs scan
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("dtype,rtol,atol", [
    (jnp.float32, 1e-5, 1e-5),
    (jnp.bfloat16, 4e-2, 4e-2),   # scan computes in bf16, kernel in f32
])
def test_fused_vs_scan_forward(dtype, rtol, atol):
    T, B, I, H = 9, 3, 5, 6
    x, params, h0, c0 = _rand_lstm(T, B, I, H, dtype=dtype)
    out_s, hT_s, cT_s = _forward(x, params, h0, c0, H, 1, fused=None)
    out_f, hT_f, cT_f = _forward(x, params, h0, c0, H, 1,
                                 fused="interpret")
    assert out_f.dtype == out_s.dtype
    for a, b in ((out_f, out_s), (hT_f, hT_s), (cT_f, cT_s)):
        onp.testing.assert_allclose(
            onp.asarray(a, onp.float32), onp.asarray(b, onp.float32),
            rtol=rtol, atol=atol)


@pytest.mark.parametrize("dtype,rtol,atol", [
    (jnp.float32, 1e-4, 1e-5),
    (jnp.bfloat16, 8e-2, 8e-2),
])
def test_fused_vs_scan_gradients(dtype, rtol, atol):
    T, B, I, H = 7, 2, 4, 5
    x, params, h0, c0 = _rand_lstm(T, B, I, H, dtype=dtype, seed=1)

    def loss(fused):
        def f(x, params, h0, c0):
            out, hT, cT = _forward(x, params, h0, c0, H, 1, fused)
            o32 = out.astype(jnp.float32)
            return ((o32 * o32).sum() + 2.0 * hT.astype(jnp.float32).sum()
                    + 3.0 * cT.astype(jnp.float32).sum())
        return f

    g_s = jax.grad(loss(None), argnums=(0, 1, 2, 3))(x, params, h0, c0)
    g_f = jax.grad(loss("interpret"), argnums=(0, 1, 2, 3))(
        x, params, h0, c0)
    for a, b in zip(g_f, g_s):
        onp.testing.assert_allclose(
            onp.asarray(a, onp.float32), onp.asarray(b, onp.float32),
            rtol=rtol, atol=atol)


def test_multilayer_fused_vs_wavefront():
    """Fused path outranks the wavefront for LSTM stacks; both must
    agree (the wavefront is numerically identical to the scan)."""
    T, B, I, H, L = 8, 3, 6, 6, 3
    x, params, h0, c0 = _rand_lstm(T, B, I, H, L=L, seed=2)
    assert os.environ.get("MXNET_RNN_WAVEFRONT", "1") != "0"
    out_w, hT_w, cT_w = _forward(x, params, h0, c0, H, L, fused=None)
    out_f, hT_f, cT_f = _forward(x, params, h0, c0, H, L,
                                 fused="interpret")
    onp.testing.assert_allclose(onp.asarray(out_f), onp.asarray(out_w),
                                rtol=1e-5, atol=1e-5)
    onp.testing.assert_allclose(onp.asarray(hT_f), onp.asarray(hT_w),
                                rtol=1e-5, atol=1e-5)
    onp.testing.assert_allclose(onp.asarray(cT_f), onp.asarray(cT_w),
                                rtol=1e-5, atol=1e-5)


def test_fused_interlayer_dropout_composes():
    """Dropout between layers runs OUTSIDE the per-layer kernels; the
    fused stack under a fixed dropout key must match the scan stack
    under the same key (identical mask draws)."""
    T, B, I, H, L = 6, 2, 4, 4, 2
    x, params, h0, c0 = _rand_lstm(T, B, I, H, L=L, seed=3)
    key = jax.random.key(7)
    out_s, _, _ = oprnn.rnn_forward(x, params, h0, c0, "lstm", H, L,
                                    dropout_rate=0.5, dropout_key=key,
                                    fused=None)
    out_f, _, _ = oprnn.rnn_forward(x, params, h0, c0, "lstm", H, L,
                                    dropout_rate=0.5, dropout_key=key,
                                    fused="interpret")
    onp.testing.assert_allclose(onp.asarray(out_f), onp.asarray(out_s),
                                rtol=1e-5, atol=1e-5)


def test_bidirectional_falls_back_to_scan():
    """The reverse direction has no fused kernel: a bidirectional stack
    must produce scan-identical output and trace ONE fused kernel per
    layer (forward direction only)."""
    T, B, I, H = 6, 2, 5, 4
    ks = jax.random.split(jax.random.key(4), 4)
    x = jax.random.normal(ks[0], (T, B, I))
    n = oprnn.param_size("lstm", I, H, 1, bidirectional=True)
    params = jax.random.normal(ks[1], (n,)) * 0.2
    h0 = jax.random.normal(ks[2], (2, B, H)) * 0.3
    c0 = jax.random.normal(ks[3], (2, B, H)) * 0.3
    out_s, hT_s, cT_s = oprnn.rnn_forward(
        x, params, h0, c0, "lstm", H, 1, bidirectional=True, fused=None)
    before = fc.trace_counts["lstm_sequence"]
    out_f, hT_f, cT_f = oprnn.rnn_forward(
        x, params, h0, c0, "lstm", H, 1, bidirectional=True,
        fused="interpret")
    assert fc.trace_counts["lstm_sequence"] == before + 1  # fwd dir only
    onp.testing.assert_allclose(onp.asarray(out_f), onp.asarray(out_s),
                                rtol=1e-5, atol=1e-5)
    onp.testing.assert_allclose(onp.asarray(hT_f), onp.asarray(hT_s),
                                rtol=1e-5, atol=1e-5)


def test_gru_ignores_fused_gate():
    """GRU falls back to scan even when the gate is forced."""
    T, B, I, H = 5, 2, 4, 4
    ks = jax.random.split(jax.random.key(5), 3)
    x = jax.random.normal(ks[0], (T, B, I))
    params = jax.random.normal(
        ks[1], (oprnn.param_size("gru", I, H),)) * 0.2
    h0 = jax.random.normal(ks[2], (1, B, H)) * 0.3
    before = fc.trace_counts["lstm_sequence"]
    out_s, _, _ = oprnn.rnn_forward(x, params, h0, None, "gru", H, 1,
                                    fused=None)
    out_f, _, _ = oprnn.rnn_forward(x, params, h0, None, "gru", H, 1,
                                    fused="interpret")
    assert fc.trace_counts["lstm_sequence"] == before
    onp.testing.assert_allclose(onp.asarray(out_f), onp.asarray(out_s),
                                rtol=1e-6, atol=1e-6)


def test_hybridized_lstm_layer_end_to_end(monkeypatch):
    """gluon rnn.LSTM, hybridized: gate off vs interpret must agree in
    forward AND parameter gradients."""
    mx.random.seed(11)
    layer = rnn.LSTM(hidden_size=8, num_layers=2)
    layer.initialize()
    x = np.random.uniform(-1, 1, size=(5, 3, 4))

    def run():
        with autograd.record():
            out = layer(x)
            loss = (out * out).sum()
        loss.backward()
        return (out.asnumpy(),
                layer.h2h_weight_l0.grad().asnumpy().copy(),
                layer.i2h_weight_l1.grad().asnumpy().copy())

    monkeypatch.setenv("MXNET_RNN_FUSED_CELL", "0")
    layer.hybridize()
    ref = run()
    monkeypatch.setenv("MXNET_RNN_FUSED_CELL", "interpret")
    before = fc.trace_counts["lstm_sequence"]
    got = run()
    assert fc.trace_counts["lstm_sequence"] > before  # actually fused
    for a, b in zip(got, ref):
        onp.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_env_gate_changes_trace_signature(monkeypatch):
    """Flipping MXNET_RNN_FUSED_CELL must change the HybridBlock trace
    signature (stale-cache guard, the MXNET_FUSE_EPILOGUE precedent)."""
    layer = rnn.LSTM(hidden_size=4)
    layer.initialize()
    x = np.random.uniform(size=(3, 2, 5))
    flat = [x._data if hasattr(x, "_data") else x]
    monkeypatch.setenv("MXNET_RNN_FUSED_CELL", "0")
    sig_off = layer._signature(flat)
    monkeypatch.setenv("MXNET_RNN_FUSED_CELL", "interpret")
    sig_on = layer._signature(flat)
    assert sig_off != sig_on


def test_rnn_mode_gate_grammar(monkeypatch):
    monkeypatch.setenv("MXNET_RNN_FUSED_CELL", "0")
    assert fc.rnn_mode() is None
    monkeypatch.setenv("MXNET_RNN_FUSED_CELL", "off")
    assert fc.rnn_mode() is None
    monkeypatch.setenv("MXNET_RNN_FUSED_CELL", "interpret")
    assert fc.rnn_mode() == "interpret"
    monkeypatch.delenv("MXNET_RNN_FUSED_CELL")
    # auto on CPU: the probe gate never turns the kernel on
    if jax.default_backend() == "cpu":
        assert fc.rnn_mode() is None


# ---------------------------------------------------------------------------
# scan-unroll remainder (satellite: ops/rnn.py audit)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("unroll", [2, 4, 8])
def test_scan_unroll_remainder_parity(monkeypatch, unroll):
    """bptt 35 is not divisible by 2/4/8: the scan remainder path must
    match unroll=1 exactly (fwd and grads)."""
    T, B, I, H = 35, 2, 4, 4
    x, params, h0, c0 = _rand_lstm(T, B, I, H, seed=6)

    def run():
        def loss(x, params, h0, c0):
            out, hT, cT = oprnn.rnn_forward(x, params, h0, c0, "lstm",
                                            H, 1, fused=None)
            return (out.astype(jnp.float32) ** 2).sum()
        val = loss(x, params, h0, c0)
        grad = jax.grad(loss, argnums=1)(x, params, h0, c0)
        return onp.asarray(val), onp.asarray(grad)

    monkeypatch.setenv("MXNET_RNN_SCAN_UNROLL", "1")
    v1, g1 = run()
    monkeypatch.setenv("MXNET_RNN_SCAN_UNROLL", str(unroll))
    vu, gu = run()
    onp.testing.assert_allclose(vu, v1, rtol=1e-6, atol=1e-6)
    onp.testing.assert_allclose(gu, g1, rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# fused decode step
# ---------------------------------------------------------------------------
def _tiny_lm():
    from mxnet_tpu.models import decoder as dec
    return dec.decoder_tiny_lm(seed=0, vocab_size=64, num_layers=2,
                               units=32, hidden_size=64, num_heads=4,
                               num_kv_heads=2, max_length=64)


@pytest.mark.parametrize("layer_group", [0, 1])
def test_fused_decode_step_parity(layer_group):
    """The fused layer-group kernel must reproduce the per-op decode
    step: bit-identical KV writes, matching greedy tokens, logits to
    f32 tolerance — including inactive (scratch-page) slots."""
    from mxnet_tpu.models import decoder as dec
    lm = _tiny_lm()
    cfg, params = lm.config, lm.jax_params()
    S, B, pps, total = 8, 4, 8, 16
    kp0 = jax.random.normal(jax.random.key(1),
                            (cfg.num_layers, cfg.num_kv_heads, total, S,
                             cfg.head_dim)) * 0.2
    vp0 = jax.random.normal(jax.random.key(2), kp0.shape) * 0.2
    tables = onp.zeros((B, pps), onp.int32)
    tables[0, :2] = [1, 2]
    tables[1, 0] = 3
    tables[2, :2] = [4, 5]
    pt = jnp.asarray(tables)
    tok = jnp.asarray(onp.array([5, 9, 11, 0], onp.int32))
    pos = jnp.asarray(onp.array([9, 3, 11, 0], onp.int32))
    act = jnp.asarray(onp.array([True, True, True, False]))
    f_ref = dec.make_decode_step(cfg, S)
    f_fus = dec.make_decode_step_fused(cfg, S, layer_group, "interpret")
    k1, v1, n1, l1 = f_ref(params, jnp.copy(kp0), jnp.copy(vp0), tok,
                           pos, pt, act)
    k2, v2, n2, l2 = f_fus(params, jnp.copy(kp0), jnp.copy(vp0), tok,
                           pos, pt, act)
    onp.testing.assert_array_equal(onp.asarray(k1), onp.asarray(k2))
    onp.testing.assert_array_equal(onp.asarray(v1), onp.asarray(v2))
    a = onp.asarray(act)
    onp.testing.assert_array_equal(onp.asarray(n1)[a], onp.asarray(n2)[a])
    onp.testing.assert_allclose(onp.asarray(l1)[a], onp.asarray(l2)[a],
                                rtol=1e-4, atol=1e-4)


def test_decode_launch_census_collapse():
    """The dispatch-count acceptance: the fused step issues ≤ 1 pallas
    launch per layer group, and its launch-class total collapses vs the
    per-op tower."""
    from mxnet_tpu.models import decoder as dec
    lm = _tiny_lm()
    cfg, params = lm.config, lm.jax_params()
    S, B, pps, total = 8, 4, 8, 16
    tower = dec.decode_launch_stats(params, cfg, S, B, pps, total,
                                    fused=False)
    fused1 = dec.decode_launch_stats(params, cfg, S, B, pps, total,
                                     fused=True, layer_group=0,
                                     mode="interpret")
    fused2 = dec.decode_launch_stats(params, cfg, S, B, pps, total,
                                     fused=True, layer_group=1,
                                     mode="interpret")
    assert fused1["layer_groups"] == 1
    assert fused1["pallas_per_group"] <= 1
    assert fused2["layer_groups"] == cfg.num_layers
    assert fused2["pallas_per_group"] <= 1
    assert tower["pallas_per_step"] == 0
    # the collapse itself: a whole layer's op tower folds into 1 launch
    assert fused1["launches_per_step"] * 3 <= tower["launches_per_step"]


def test_engine_fused_decode_end_to_end(monkeypatch):
    """DecodeEngine under MXNET_DECODE_FUSED=interpret: same greedy
    tokens as the per-op engine, and the launch census lands in
    stats()/metrics ('≤ 1 launch per layer group per token')."""
    from mxnet_tpu.serving.generate import DecodeEngine
    lm = _tiny_lm()
    prompts = [[1, 2, 3], [9, 8, 7, 6], [5]]

    def run(env):
        if env is None:
            monkeypatch.delenv("MXNET_DECODE_FUSED", raising=False)
        else:
            monkeypatch.setenv("MXNET_DECODE_FUSED", env)
        eng = DecodeEngine(lm, name="llm", slots=2, page_size=8,
                           prefill_chunk=8, max_ctx=64)
        futs = [eng.submit(p, max_new_tokens=6) for p in prompts]
        toks = [f.result(timeout=120)["tokens"] for f in futs]
        stats = eng.stats()
        snap = eng.metrics.snapshot()["models"]["llm"]
        eng.stop()
        assert eng.alloc.num_used == 0
        return toks, stats, snap

    toks_ref, stats_ref, _ = run("0")
    assert stats_ref["decode_fused"] is None
    toks_fus, stats_fus, snap = run("interpret")
    assert toks_fus == toks_ref
    assert stats_fus["decode_fused"] == "interpret"
    launches = stats_fus["launches"]
    assert launches["fused"] is True
    assert launches["pallas_per_group"] <= 1
    assert launches["launches_per_step"] < \
        stats_ref["launches"]["launches_per_step"]
    gen = snap["generate"]
    assert gen["decode_launches"]["pallas_per_group"] <= 1
    assert gen["fn_cache"]["compiles"] >= 1


# ---------------------------------------------------------------------------
# bounded decode/prefill program cache (satellite)
# ---------------------------------------------------------------------------
def test_fn_cache_lru_eviction(monkeypatch):
    from mxnet_tpu.models import decoder as dec
    lm = _tiny_lm()
    cfg = lm.config
    monkeypatch.setenv("MXNET_GEN_FN_CACHE", "2")
    dec._fn_cache.clear()
    try:
        f4 = dec.make_decode_step(cfg, 4)
        f8 = dec.make_decode_step(cfg, 8)
        assert dec.make_decode_step(cfg, 8) is f8       # hit
        assert dec.fn_cache_stats()["compiles"] == 2
        dec.make_decode_step(cfg, 16)                   # evicts ps=4
        st = dec.fn_cache_stats()
        assert st == {"size": 2, "cap": 2, "compiles": 3, "evictions": 1}
        assert dec.make_decode_step(cfg, 4) is not f4   # was evicted
        assert dec.fn_cache_stats()["compiles"] == 4
    finally:
        dec._fn_cache.clear()


# ---------------------------------------------------------------------------
# steplat tier-1 gate (satellite: CI asserts launches/step, not timings)
# ---------------------------------------------------------------------------
def test_steplat_launch_gate():
    import benchmark.steplat as steplat
    lstm = steplat.lstm_steplat(T=12, B=2, I=8, H=8, L=2, measure=False,
                                fused_mode="interpret")
    # fused: exactly one persistent kernel per layer, and the per-step
    # launch census collapses vs the scan tower
    assert lstm["fused"]["pallas_total"] == 2
    assert lstm["fused"]["launches_total"] * 2 \
        <= lstm["scan"]["launches_total"]
    dec = steplat.decode_steplat(measure=False, fused_mode="interpret",
                                 slots=2, page_size=8)
    assert dec["fused"]["pallas_per_group"] <= 1
    assert dec["fused"]["launches_per_step"] * 3 \
        <= dec["tower"]["launches_per_step"]
