"""Broad mx.np vs NumPy sweep (reference: test_numpy_op.py's
hypothesis-style per-op verification across the namespace —
tests/python/unittest/test_numpy_op.py, numpy interop protocol
test_numpy_interoperability.py)."""
import numpy as onp
import pytest

from mxnet_tpu import np as mxnp


RNG = onp.random.RandomState(42)


def _pos(shape):
    return RNG.rand(*shape).astype(onp.float32) + 0.1


def _any(shape):
    return (RNG.rand(*shape).astype(onp.float32) - 0.5) * 4


UNARY = [
    ("exp", _any, {}), ("expm1", _any, {}), ("log", _pos, {}),
    ("log2", _pos, {}), ("log10", _pos, {}), ("log1p", _pos, {}),
    ("sqrt", _pos, {}), ("cbrt", _any, {}), ("square", _any, {}),
    ("abs", _any, {}), ("sign", _any, {}), ("floor", _any, {}),
    ("ceil", _any, {}), ("trunc", _any, {}), ("rint", _any, {}),
    ("sin", _any, {}), ("cos", _any, {}), ("tan", _any, {}),
    ("arcsin", lambda s: _any(s) / 4, {}),
    ("arccos", lambda s: _any(s) / 4, {}),
    ("arctan", _any, {}), ("sinh", _any, {}), ("cosh", _any, {}),
    ("tanh", _any, {}), ("arcsinh", _any, {}),
    ("arccosh", lambda s: _pos(s) + 1.0, {}),
    ("arctanh", lambda s: _any(s) / 4, {}),
    ("degrees", _any, {}), ("radians", _any, {}),
    ("reciprocal", _pos, {}), ("negative", _any, {}),
    ("isnan", _any, {}), ("isinf", _any, {}), ("isfinite", _any, {}),
    ("logical_not", _any, {}),
]


@pytest.mark.parametrize("name,gen,kw", UNARY, ids=[u[0] for u in UNARY])
def test_unary_matches_numpy(name, gen, kw):
    for shape in [(7,), (3, 5), (2, 3, 4)]:
        a = gen(shape)
        got = getattr(mxnp, name)(mxnp.array(a), **kw).asnumpy()
        want = getattr(onp, name)(a, **kw)
        onp.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


BINARY = ["add", "subtract", "multiply", "true_divide", "maximum",
          "minimum", "arctan2", "hypot", "copysign", "fmod",
          "logical_and", "logical_or", "logical_xor",
          "less", "less_equal", "greater", "greater_equal", "equal",
          "not_equal"]


@pytest.mark.parametrize("name", BINARY)
def test_binary_matches_numpy_with_broadcast(name):
    for sa, sb in [((4, 5), (4, 5)), ((4, 5), (5,)), ((3, 1, 2), (4, 2))]:
        a, b = _pos(sa), _pos(sb)
        got = getattr(mxnp, name)(mxnp.array(a), mxnp.array(b)).asnumpy()
        want = getattr(onp, name)(a, b)
        onp.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


REDUCTIONS = ["sum", "prod", "mean", "std", "var", "min", "max",
              "argmin", "argmax", "nansum", "nanprod", "nanmin", "nanmax",
              "count_nonzero"]


@pytest.mark.parametrize("name", REDUCTIONS)
@pytest.mark.parametrize("axis", [None, 0, 1, -1])
def test_reductions_match_numpy(name, axis):
    a = _any((4, 5))
    got = getattr(mxnp, name)(mxnp.array(a), axis=axis)
    got = got.asnumpy() if hasattr(got, "asnumpy") else onp.asarray(got)
    want = getattr(onp, name)(a, axis=axis)
    onp.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-5)


SHAPES = [
    ("reshape", lambda a: a.reshape(5, 4), lambda a: a.reshape(5, 4)),
    ("ravel", lambda a: mxnp.ravel(a), lambda a: onp.ravel(a)),
    ("transpose", lambda a: mxnp.transpose(a), lambda a: onp.transpose(a)),
    ("flipud", lambda a: mxnp.flipud(a), lambda a: onp.flipud(a)),
    ("fliplr", lambda a: mxnp.fliplr(a), lambda a: onp.fliplr(a)),
    ("rot90", lambda a: mxnp.rot90(a), lambda a: onp.rot90(a)),
    ("roll", lambda a: mxnp.roll(a, 2), lambda a: onp.roll(a, 2)),
    ("tile", lambda a: mxnp.tile(a, (2, 1)), lambda a: onp.tile(a, (2, 1))),
    ("repeat", lambda a: mxnp.repeat(a, 2, axis=0),
     lambda a: onp.repeat(a, 2, axis=0)),
    ("expand_dims", lambda a: mxnp.expand_dims(a, 1),
     lambda a: onp.expand_dims(a, 1)),
    ("squeeze", lambda a: mxnp.squeeze(mxnp.expand_dims(a, 0)),
     lambda a: onp.squeeze(onp.expand_dims(a, 0))),
    ("swapaxes", lambda a: mxnp.swapaxes(a, 0, 1),
     lambda a: onp.swapaxes(a, 0, 1)),
    ("moveaxis", lambda a: mxnp.moveaxis(a, 0, 1),
     lambda a: onp.moveaxis(a, 0, 1)),
    ("atleast_2d", lambda a: mxnp.atleast_2d(a),
     lambda a: onp.atleast_2d(a)),
    ("tril", lambda a: mxnp.tril(a), lambda a: onp.tril(a)),
    ("triu", lambda a: mxnp.triu(a), lambda a: onp.triu(a)),
    ("diff", lambda a: mxnp.diff(a, axis=1), lambda a: onp.diff(a, axis=1)),
    ("cumsum", lambda a: mxnp.cumsum(a, axis=1),
     lambda a: onp.cumsum(a, axis=1)),
    ("cumprod", lambda a: mxnp.cumprod(a, axis=1),
     lambda a: onp.cumprod(a, axis=1)),
    ("sort", lambda a: mxnp.sort(a, axis=1), lambda a: onp.sort(a, axis=1)),
    ("argsort", lambda a: mxnp.argsort(a, axis=1),
     lambda a: onp.argsort(a, axis=1)),
    ("pad", lambda a: mxnp.pad(a, ((1, 1), (2, 0))),
     lambda a: onp.pad(a, ((1, 1), (2, 0)))),
    ("clip", lambda a: mxnp.clip(a, -0.5, 0.5),
     lambda a: onp.clip(a, -0.5, 0.5)),
    ("nan_to_num", lambda a: mxnp.nan_to_num(a),
     lambda a: onp.nan_to_num(a)),
    ("trace", lambda a: mxnp.trace(a), lambda a: onp.trace(a)),
    ("diag", lambda a: mxnp.diag(a), lambda a: onp.diag(a)),
]


@pytest.mark.parametrize("name,mxf,onf", SHAPES, ids=[s[0] for s in SHAPES])
def test_shape_ops_match_numpy(name, mxf, onf):
    a = _any((4, 5))
    got = mxf(mxnp.array(a))
    got = got.asnumpy() if hasattr(got, "asnumpy") else onp.asarray(got)
    onp.testing.assert_allclose(got, onf(a), rtol=2e-5, atol=2e-6)


COMBINE = [
    ("concatenate", lambda xs: mxnp.concatenate(xs, axis=0),
     lambda xs: onp.concatenate(xs, axis=0)),
    ("stack", lambda xs: mxnp.stack(xs), lambda xs: onp.stack(xs)),
    ("vstack", lambda xs: mxnp.vstack(xs), lambda xs: onp.vstack(xs)),
    ("hstack", lambda xs: mxnp.hstack(xs), lambda xs: onp.hstack(xs)),
    ("dstack", lambda xs: mxnp.dstack(xs), lambda xs: onp.dstack(xs)),
    ("column_stack", lambda xs: mxnp.column_stack(xs),
     lambda xs: onp.column_stack(xs)),
]


@pytest.mark.parametrize("name,mxf,onf", COMBINE, ids=[c[0] for c in COMBINE])
def test_combine_ops_match_numpy(name, mxf, onf):
    xs = [_any((3, 4)), _any((3, 4))]
    got = mxf([mxnp.array(x) for x in xs]).asnumpy()
    onp.testing.assert_allclose(got, onf(xs), rtol=2e-5)


def test_linalg_matches_numpy():
    a = _any((4, 4))
    spd = a @ a.T + 4 * onp.eye(4, dtype=onp.float32)
    ma = mxnp.array(spd)
    onp.testing.assert_allclose(mxnp.linalg.det(ma).asnumpy(),
                                onp.linalg.det(spd), rtol=1e-4)
    onp.testing.assert_allclose(
        mxnp.linalg.inv(ma).asnumpy(), onp.linalg.inv(spd), rtol=1e-3,
        atol=1e-4)
    L = mxnp.linalg.cholesky(ma).asnumpy()
    onp.testing.assert_allclose(L @ L.T, spd, rtol=1e-4, atol=1e-4)
    onp.testing.assert_allclose(mxnp.linalg.norm(ma).asnumpy(),
                                onp.linalg.norm(spd), rtol=1e-5)
    w_got = onp.sort(mxnp.linalg.eigvalsh(ma).asnumpy())
    w_ref = onp.sort(onp.linalg.eigvalsh(spd))
    onp.testing.assert_allclose(w_got, w_ref, rtol=1e-4)
    b = _any((4, 2))
    onp.testing.assert_allclose(
        mxnp.linalg.solve(ma, mxnp.array(b)).asnumpy(),
        onp.linalg.solve(spd, b), rtol=1e-3, atol=1e-4)


def test_einsum_variants_match_numpy():
    a, b = _any((3, 4)), _any((4, 5))
    for expr, ops in [("ij,jk->ik", (a, b)),
                      ("ij->ji", (a,)),
                      ("ij->", (a,)),
                      ("ij,ij->i", (a, a))]:
        got = mxnp.einsum(expr, *[mxnp.array(x) for x in ops]).asnumpy()
        onp.testing.assert_allclose(got, onp.einsum(expr, *ops),
                                    rtol=1e-4, atol=1e-5)


def test_batchify_functions():
    from mxnet_tpu.gluon.data import batchify
    s = batchify.Stack()([onp.ones((2, 3)), onp.zeros((2, 3))])
    assert s.shape == (2, 2, 3)
    p, lens = batchify.Pad(axis=0, pad_val=-1, ret_length=True)(
        [onp.ones(3), onp.ones(5)])
    assert p.shape == (2, 5)
    onp.testing.assert_array_equal(p.asnumpy()[0], [1, 1, 1, -1, -1])
    onp.testing.assert_array_equal(lens.asnumpy(), [3, 5])
    g = batchify.Group(batchify.Stack(), batchify.Pad(pad_val=0))(
        [(onp.ones(2), onp.ones(3)), (onp.zeros(2), onp.ones(4))])
    assert g[0].shape == (2, 2) and g[1].shape == (2, 4)


def test_batchify_in_dataloader():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader, batchify
    xs = [onp.ones(i + 1, onp.float32) for i in range(6)]
    ys = onp.arange(6, dtype=onp.float32)
    ds = [(x, y) for x, y in zip(xs, ys)]
    loader = DataLoader(ds, batch_size=3,
                        batchify_fn=batchify.Group(
                            batchify.Pad(pad_val=0), batchify.Stack()))
    batches = list(loader)
    assert len(batches) == 2
    x0, y0 = batches[0]
    assert x0.shape == (3, 3)  # padded to the longest in batch
