"""Gluon layer tests + the imperative-vs-hybridized consistency oracle
(reference analog: tests/python/unittest/test_gluon.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np, npx, gluon, autograd
from mxnet_tpu.gluon import nn


def check_layer(layer, in_shape, eval_mode=True, rtol=1e-4, atol=1e-5):
    """The hybridize-consistency oracle: same outputs eager vs compiled."""
    mx.random.seed(0)
    layer.initialize()
    x = np.random.uniform(-1, 1, size=in_shape)
    if eval_mode:
        eager = layer(x).asnumpy()
        layer.hybridize()
        hybrid = layer(x).asnumpy()
        onp.testing.assert_allclose(eager, hybrid, rtol=rtol, atol=atol)
        return eager
    return layer(x).asnumpy()


def test_dense():
    out = check_layer(nn.Dense(8), (4, 6))
    assert out.shape == (4, 8)
    out = check_layer(nn.Dense(8, activation="relu", flatten=False), (2, 3, 6))
    assert out.shape == (2, 3, 8)
    assert (out >= 0).all()
    out = check_layer(nn.Dense(5, use_bias=False), (4, 6))
    assert out.shape == (4, 5)


def test_dense_vs_numpy():
    net = nn.Dense(3, in_units=4)
    net.initialize()
    x = np.random.uniform(size=(2, 4))
    w = net.weight.data().asnumpy()
    b = net.bias.data().asnumpy()
    expect = x.asnumpy() @ w.T + b
    onp.testing.assert_allclose(net(x).asnumpy(), expect, rtol=1e-5)


@pytest.mark.parametrize("layer_fn,shape", [
    (lambda: nn.Conv1D(4, 3), (2, 3, 10)),
    (lambda: nn.Conv2D(4, 3), (2, 3, 10, 10)),
    (lambda: nn.Conv2D(4, 3, strides=2, padding=1), (2, 3, 10, 10)),
    (lambda: nn.Conv2D(4, 3, dilation=2), (2, 3, 12, 12)),
    (lambda: nn.Conv2D(4, 3, groups=2), (2, 4, 8, 8)),
    (lambda: nn.Conv3D(4, 3), (2, 3, 6, 6, 6)),
    (lambda: nn.Conv2DTranspose(4, 3), (2, 3, 8, 8)),
    (lambda: nn.Conv2DTranspose(4, 3, strides=2), (2, 3, 8, 8)),
])
def test_conv_layers(layer_fn, shape):
    check_layer(layer_fn(), shape)


def test_conv2d_vs_numpy():
    """Convolution numerical check vs explicit loop."""
    net = nn.Conv2D(2, kernel_size=2, in_channels=1, use_bias=False)
    net.initialize()
    x = np.random.uniform(size=(1, 1, 4, 4))
    w = net.weight.data().asnumpy()
    xn = x.asnumpy()
    out = net(x).asnumpy()
    expect = onp.zeros((1, 2, 3, 3), "float32")
    for oc in range(2):
        for i in range(3):
            for j in range(3):
                expect[0, oc, i, j] = (xn[0, 0, i:i + 2, j:j + 2]
                                       * w[oc, 0]).sum()
    onp.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_conv2dtranspose_shape():
    # MXNet: out = (in-1)*s - 2p + k + adj
    net = nn.Conv2DTranspose(3, kernel_size=4, strides=2, padding=1,
                             output_padding=0)
    net.initialize()
    out = net(np.zeros((1, 2, 8, 8)))
    assert out.shape == (1, 3, 16, 16)


@pytest.mark.parametrize("layer_fn,shape,out_shape", [
    (lambda: nn.MaxPool2D(2), (1, 2, 8, 8), (1, 2, 4, 4)),
    (lambda: nn.MaxPool2D(3, 2, 1), (1, 2, 8, 8), (1, 2, 4, 4)),
    (lambda: nn.AvgPool2D(2), (1, 2, 8, 8), (1, 2, 4, 4)),
    (lambda: nn.MaxPool1D(2), (1, 2, 8), (1, 2, 4)),
    (lambda: nn.AvgPool3D(2), (1, 2, 4, 4, 4), (1, 2, 2, 2, 2)),
    (lambda: nn.GlobalAvgPool2D(), (1, 2, 8, 8), (1, 2, 1, 1)),
    (lambda: nn.GlobalMaxPool2D(), (1, 2, 8, 8), (1, 2, 1, 1)),
])
def test_pool_layers(layer_fn, shape, out_shape):
    out = check_layer(layer_fn(), shape)
    assert out.shape == out_shape


def test_pool_values():
    x = np.array(onp.arange(16.0, dtype="float32").reshape(1, 1, 4, 4))
    mp = nn.MaxPool2D(2)
    onp.testing.assert_array_equal(mp(x).asnumpy().ravel(), [5, 7, 13, 15])
    ap = nn.AvgPool2D(2)
    onp.testing.assert_allclose(ap(x).asnumpy().ravel(), [2.5, 4.5, 10.5, 12.5])


def test_pool_ceil_mode():
    x = np.zeros((1, 1, 5, 5))
    out = nn.MaxPool2D(2, strides=2, ceil_mode=True)(x)
    assert out.shape == (1, 1, 3, 3)
    out = nn.MaxPool2D(2, strides=2, ceil_mode=False)(x)
    assert out.shape == (1, 1, 2, 2)


def test_batchnorm_train_inference():
    net = nn.BatchNorm()
    net.initialize()
    x = np.random.normal(3.0, 2.0, size=(16, 4, 5, 5))
    # training: output should be ~normalized
    with autograd.record():
        out = net(x)
    o = out.asnumpy()
    assert abs(o.mean()) < 0.1
    assert abs(o.std() - 1.0) < 0.1
    # running stats moved toward batch stats
    rm = net.running_mean.data().asnumpy()
    assert abs(rm.mean() - 0.3) < 0.15  # momentum 0.9: 0.1 * ~3.0
    # inference uses running stats (deterministic)
    out1 = net(x).asnumpy()
    out2 = net(x).asnumpy()
    onp.testing.assert_array_equal(out1, out2)


def test_layernorm_groupnorm_instancenorm():
    out = check_layer(nn.LayerNorm(), (4, 10))
    assert abs(out.mean()) < 1e-5
    assert abs(out.std() - 1.0) < 0.05
    check_layer(nn.GroupNorm(num_groups=2), (2, 4, 5, 5))
    check_layer(nn.InstanceNorm(), (2, 4, 5, 5))


def test_embedding():
    net = nn.Embedding(10, 4)
    net.initialize()
    idx = np.array([[1, 2], [3, 9]], dtype="int32")
    out = net(idx)
    assert out.shape == (2, 2, 4)
    w = net.weight.data().asnumpy()
    onp.testing.assert_allclose(out.asnumpy()[0, 0], w[1], rtol=1e-6)


def test_embedding_grad_accumulates():
    net = nn.Embedding(5, 3)
    net.initialize()
    idx = np.array([0, 0, 1], dtype="int32")
    with autograd.record():
        out = net(idx).sum()
    out.backward()
    g = net.weight.grad().asnumpy()
    onp.testing.assert_allclose(g[0], [2, 2, 2], rtol=1e-6)  # row 0 used twice
    onp.testing.assert_allclose(g[1], [1, 1, 1], rtol=1e-6)
    onp.testing.assert_allclose(g[2], [0, 0, 0], rtol=1e-6)


@pytest.mark.parametrize("act", ["relu", "sigmoid", "tanh", "softrelu",
                                 "softsign", "gelu", "silu", "mish"])
def test_activations(act):
    check_layer(nn.Activation(act), (2, 5))


def test_activation_classes():
    check_layer(nn.LeakyReLU(0.1), (2, 5))
    check_layer(nn.ELU(), (2, 5))
    check_layer(nn.SELU(), (2, 5))
    check_layer(nn.GELU(), (2, 5))
    check_layer(nn.Swish(), (2, 5))
    check_layer(nn.SiLU(), (2, 5))
    check_layer(nn.PReLU(), (2, 5))


def test_sequential_containers():
    for cls in (nn.Sequential, nn.HybridSequential):
        net = cls()
        net.add(nn.Dense(8, activation="relu"), nn.Dense(4))
        assert len(net) == 2
        net.initialize()
        out = net(np.ones((2, 6)))
        assert out.shape == (2, 4)
        assert isinstance(net[0], nn.Dense)


def test_flatten_identity_lambda():
    assert check_layer(nn.Flatten(), (2, 3, 4)).shape == (2, 12)
    assert check_layer(nn.Identity(), (2, 3)).shape == (2, 3)
    lam = nn.HybridLambda(lambda x: x * 2)
    out = lam(np.ones((2, 2)))
    onp.testing.assert_array_equal(out.asnumpy(), 2 * onp.ones((2, 2)))


def test_dropout_modes():
    net = nn.Dropout(0.5)
    x = np.ones((10, 10))
    out = net(x)  # inference: identity
    onp.testing.assert_array_equal(out.asnumpy(), onp.ones((10, 10)))
    with autograd.train_mode():
        out = net(x).asnumpy()
    assert (out == 0).any()
    kept = out[out != 0]
    onp.testing.assert_allclose(kept, 2.0 * onp.ones_like(kept), rtol=1e-6)


def test_collect_params_naming():
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(2))
    params = net.collect_params()
    names = list(params)
    assert any("0.weight" in n for n in names)
    assert any("1.bias" in n for n in names)
    sel = net.collect_params(".*weight")
    assert all("weight" in n for n in sel)


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net.initialize()
    f = str(tmp_path / "p.npz")
    net.save_parameters(f)
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net2.initialize()
    net2.load_parameters(f)
    x = np.random.uniform(size=(2, 3))
    onp.testing.assert_allclose(net(x).asnumpy(), net2(x).asnumpy(), rtol=1e-6)


def test_deferred_init_then_train():
    net = nn.HybridSequential()
    net.add(nn.Dense(4, activation="relu"), nn.Dense(2))
    net.initialize()
    # shapes unknown until first forward
    assert net[0].weight._data is None
    out = net(np.ones((2, 7)))
    assert net[0].weight.shape == (4, 7)
    assert out.shape == (2, 2)


def test_shared_parameter_grads_sum():
    d = nn.Dense(3, in_units=3)
    d.initialize()
    x = np.ones((1, 3))
    with autograd.record():
        y = d(d(x)).sum()
    y.backward()
    g = d.weight.grad().asnumpy()
    assert onp.abs(g).sum() > 0


def test_hybridize_training_consistency():
    """Eager and hybridized nets starting from identical params converge
    identically under SGD (the strongest §4 oracle)."""
    def build():
        mx.random.seed(7)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
        net.initialize()
        net(np.ones((2, 8)))  # init shapes
        return net

    x = np.random.uniform(size=(8, 8))
    y = np.random.randint(0, 4, size=(8,))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    results = []
    for hybrid in (False, True):
        net = build()
        if hybrid:
            net.hybridize()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        losses = []
        for _ in range(5):
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(8)
            losses.append(float(loss.mean()))
        results.append(losses)
    onp.testing.assert_allclose(results[0], results[1], rtol=1e-4, atol=1e-5)


def test_constant_param():
    c = gluon.Constant(np.array([1.0, 2.0]))
    c.initialize()
    onp.testing.assert_array_equal(c.data().asnumpy(), [1, 2])


def test_cast_dtype():
    net = nn.Dense(4, in_units=3)
    net.initialize()
    net.cast("float16")
    assert net.weight.data().dtype == onp.float16


def test_summary(capsys):
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3))
    net.initialize()
    net.summary(np.ones((1, 3)))
    out = capsys.readouterr().out
    assert "Total params" in out
