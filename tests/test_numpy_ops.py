"""mx.np operator coverage vs NumPy reference (reference analog:
tests/python/unittest/test_numpy_op.py — numeric verification against
NumPy)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np


def _check(mx_fn, np_fn, *shapes, rtol=1e-5, atol=1e-6, dtype="float32",
           positive=False):
    rng = onp.random.RandomState(0)
    args_np = []
    for s in shapes:
        a = rng.uniform(0.5 if positive else -2.0, 2.0, s).astype(dtype)
        args_np.append(a)
    args_mx = [np.array(a) for a in args_np]
    out_mx = mx_fn(*args_mx)
    out_np = np_fn(*args_np)
    onp.testing.assert_allclose(out_mx.asnumpy(), out_np, rtol=rtol, atol=atol)


UNARY_CASES = [
    ("abs", None), ("sqrt", "pos"), ("square", None), ("exp", None),
    ("log", "pos"), ("log2", "pos"), ("log10", "pos"), ("log1p", "pos"),
    ("sin", None), ("cos", None), ("tan", None), ("tanh", None),
    ("sinh", None), ("cosh", None), ("arctan", None), ("ceil", None),
    ("floor", None), ("rint", None), ("sign", None), ("negative", None),
    ("reciprocal", "pos"), ("expm1", None), ("cbrt", None),
    ("degrees", None), ("radians", None),
]


@pytest.mark.parametrize("name,mode", UNARY_CASES)
def test_unary(name, mode):
    _check(getattr(np, name), getattr(onp, name), (3, 4),
           positive=(mode == "pos"), rtol=1e-4, atol=1e-5)


BINARY_CASES = ["add", "subtract", "multiply", "maximum", "minimum",
                "arctan2", "hypot", "logaddexp", "copysign"]


@pytest.mark.parametrize("name", BINARY_CASES)
def test_binary(name):
    _check(getattr(np, name), getattr(onp, name), (3, 4), (3, 4), rtol=1e-4)


def test_divide_power():
    _check(np.true_divide, onp.true_divide, (3, 4), (3, 4), positive=True)
    _check(np.power, onp.power, (3, 4), (3, 4), positive=True, rtol=1e-3)
    _check(np.mod, onp.mod, (3, 4), (3, 4), positive=True, rtol=1e-4)


REDUCE_CASES = ["sum", "prod", "mean", "std", "var", "max", "min"]


@pytest.mark.parametrize("name", REDUCE_CASES)
@pytest.mark.parametrize("axis", [None, 0, 1, (0, 1)])
def test_reductions(name, axis):
    _check(lambda a: getattr(np, name)(a, axis=axis),
           lambda a: getattr(onp, name)(a, axis=axis), (3, 4), rtol=1e-4)


def test_argminmax_cumsum():
    a = onp.random.RandomState(1).randn(4, 5).astype("float32")
    m = np.array(a)
    assert np.argmax(m).item() == a.argmax()
    onp.testing.assert_array_equal(np.argmin(m, axis=1).asnumpy(),
                                   a.argmin(axis=1))
    onp.testing.assert_allclose(np.cumsum(m, axis=0).asnumpy(),
                                a.cumsum(axis=0), rtol=1e-5)


def test_shape_manipulation():
    a = onp.arange(24, dtype="float32").reshape(2, 3, 4)
    m = np.array(a)
    onp.testing.assert_array_equal(np.reshape(m, (6, 4)).asnumpy(),
                                   a.reshape(6, 4))
    onp.testing.assert_array_equal(np.transpose(m, (2, 0, 1)).asnumpy(),
                                   a.transpose(2, 0, 1))
    onp.testing.assert_array_equal(np.swapaxes(m, 0, 2).asnumpy(),
                                   a.swapaxes(0, 2))
    onp.testing.assert_array_equal(np.moveaxis(m, 0, -1).asnumpy(),
                                   onp.moveaxis(a, 0, -1))
    onp.testing.assert_array_equal(np.expand_dims(m, 1).shape, (2, 1, 3, 4))
    onp.testing.assert_array_equal(np.squeeze(np.expand_dims(m, 0)).asnumpy(), a)
    onp.testing.assert_array_equal(np.flip(m, 1).asnumpy(), onp.flip(a, 1))
    onp.testing.assert_array_equal(np.roll(m, 2, 1).asnumpy(), onp.roll(a, 2, 1))
    onp.testing.assert_array_equal(np.tile(m, (1, 2, 1)).asnumpy(),
                                   onp.tile(a, (1, 2, 1)))
    onp.testing.assert_array_equal(np.repeat(m, 2, 0).asnumpy(),
                                   onp.repeat(a, 2, 0))
    onp.testing.assert_array_equal(np.broadcast_to(np.ones((1, 3)), (4, 3)).shape,
                                   (4, 3))


def test_concat_stack_split():
    a = onp.ones((2, 3), "float32")
    b = onp.zeros((2, 3), "float32")
    ma, mb = np.array(a), np.array(b)
    onp.testing.assert_array_equal(np.concatenate([ma, mb]).asnumpy(),
                                   onp.concatenate([a, b]))
    onp.testing.assert_array_equal(
        np.concatenate([ma, mb], axis=1).asnumpy(),
        onp.concatenate([a, b], axis=1))
    onp.testing.assert_array_equal(np.stack([ma, mb]).asnumpy(),
                                   onp.stack([a, b]))
    onp.testing.assert_array_equal(np.vstack([ma, mb]).asnumpy(),
                                   onp.vstack([a, b]))
    onp.testing.assert_array_equal(np.hstack([ma, mb]).asnumpy(),
                                   onp.hstack([a, b]))
    parts = np.split(np.array(onp.arange(12.0)), 3)
    assert len(parts) == 3
    onp.testing.assert_array_equal(parts[1].asnumpy(), [4, 5, 6, 7])


def test_linalg_family():
    rng = onp.random.RandomState(0)
    a = rng.randn(4, 4).astype("float32")
    spd = a @ a.T + 4 * onp.eye(4, dtype="float32")
    m = np.array(spd)
    onp.testing.assert_allclose(np.linalg.det(m).item(),
                                onp.linalg.det(spd), rtol=1e-3)
    onp.testing.assert_allclose(np.linalg.inv(m).asnumpy(),
                                onp.linalg.inv(spd), rtol=1e-3, atol=1e-4)
    L = np.linalg.cholesky(m).asnumpy()
    onp.testing.assert_allclose(L @ L.T, spd, rtol=1e-4, atol=1e-4)
    b = rng.randn(4).astype("float32")
    onp.testing.assert_allclose(
        np.linalg.solve(m, np.array(b)).asnumpy(),
        onp.linalg.solve(spd, b), rtol=1e-3, atol=1e-4)
    onp.testing.assert_allclose(np.linalg.norm(m).item(),
                                onp.linalg.norm(spd), rtol=1e-5)
    u, s, v = np.linalg.svd(np.array(a))
    onp.testing.assert_allclose(
        (u.asnumpy() * s.asnumpy()) @ v.asnumpy(), a, rtol=1e-3, atol=1e-4)


def test_einsum_dot():
    rng = onp.random.RandomState(0)
    a = rng.randn(3, 4).astype("float32")
    b = rng.randn(4, 5).astype("float32")
    onp.testing.assert_allclose(
        np.einsum("ij,jk->ik", np.array(a), np.array(b)).asnumpy(),
        onp.einsum("ij,jk->ik", a, b), rtol=1e-4)
    onp.testing.assert_allclose(np.dot(np.array(a), np.array(b)).asnumpy(),
                                a @ b, rtol=1e-4)
    onp.testing.assert_allclose(
        np.tensordot(np.array(a), np.array(b), axes=([1], [0])).asnumpy(),
        onp.tensordot(a, b, axes=([1], [0])), rtol=1e-4)


def test_where_clip_round():
    a = onp.array([[-1.0, 2.0], [3.0, -4.0]], dtype="float32")
    m = np.array(a)
    onp.testing.assert_array_equal(
        np.where(m > 0, m, np.zeros_like(m)).asnumpy(),
        onp.where(a > 0, a, 0))
    onp.testing.assert_array_equal(np.clip(m, -1, 1).asnumpy(),
                                   a.clip(-1, 1))
    onp.testing.assert_array_equal(np.round(m * 0.6).asnumpy(),
                                   onp.round(a * 0.6))


def test_sort_unique_searchsorted():
    a = onp.array([3.0, 1.0, 2.0, 1.0], dtype="float32")
    m = np.array(a)
    onp.testing.assert_array_equal(np.sort(m).asnumpy(), onp.sort(a))
    onp.testing.assert_array_equal(np.argsort(m).asnumpy(), onp.argsort(a))
    u = np.unique(m)
    onp.testing.assert_array_equal(u.asnumpy(), [1, 2, 3])
    onp.testing.assert_array_equal(
        np.searchsorted(np.array([1.0, 2.0, 3.0]), np.array([2.5])).asnumpy(),
        [2])


def test_creation_dtypes_and_constants():
    assert np.pi == onp.pi
    assert np.float32 is onp.float32
    # TPU-native deviation: 64-bit ints truncate to int32 (the TPU ALU
    # width); reference uses int64 indices on CPU/GPU.
    z = np.zeros((2,), dtype=np.int32)
    assert z.dtype == onp.int32
    assert np.finfo(np.float32).eps == onp.finfo(onp.float32).eps


@pytest.mark.slow
def test_random_distributions_shapes():
    assert np.random.uniform(0, 1, size=(3, 4)).shape == (3, 4)
    assert np.random.normal(0, 1, size=5).shape == (5,)
    assert np.random.randint(0, 10, size=(2, 2)).dtype == onp.int32
    assert np.random.gamma(2.0, 1.0, size=(4,)).shape == (4,)
    assert np.random.beta(2.0, 3.0, size=(4,)).shape == (4,)
    assert np.random.exponential(1.0, size=(4,)).shape == (4,)
    assert np.random.poisson(3.0, size=(4,)).shape == (4,)
    assert np.random.choice(10, size=(3,)).shape == (3,)
    assert np.random.laplace(size=(2, 2)).shape == (2, 2)
    assert np.random.gumbel(size=(2,)).shape == (2,)
    assert np.random.chisquare(3.0, size=(2,)).shape == (2,)


def test_random_determinism():
    mx.random.seed(42)
    a = np.random.uniform(size=(4,)).asnumpy()
    mx.random.seed(42)
    b = np.random.uniform(size=(4,)).asnumpy()
    onp.testing.assert_array_equal(a, b)
    c = np.random.uniform(size=(4,)).asnumpy()
    assert not onp.array_equal(b, c)


def test_random_moments():
    mx.random.seed(0)
    x = np.random.normal(2.0, 3.0, size=(20000,)).asnumpy()
    assert abs(x.mean() - 2.0) < 0.1
    assert abs(x.std() - 3.0) < 0.1
    u = np.random.uniform(1.0, 5.0, size=(20000,)).asnumpy()
    assert abs(u.mean() - 3.0) < 0.05
    assert u.min() >= 1.0 and u.max() <= 5.0


def test_histogram_bincount():
    a = onp.array([0.5, 1.5, 1.6, 2.5], dtype="float32")
    h, edges = np.histogram(np.array(a), bins=3, range=(0, 3))
    onp.testing.assert_array_equal(h.asnumpy(), [1, 2, 1])
    b = np.bincount(np.array([0, 1, 1, 2], dtype="int32"))
    onp.testing.assert_array_equal(b.asnumpy(), [1, 2, 1])


def test_diff_interp_trace():
    a = onp.array([1.0, 3.0, 6.0], dtype="float32")
    onp.testing.assert_array_equal(np.diff(np.array(a)).asnumpy(),
                                   onp.diff(a))
    onp.testing.assert_allclose(
        np.interp(np.array([1.5]), np.array([1.0, 2.0]),
                  np.array([10.0, 20.0])).asnumpy(), [15.0])
    m = onp.arange(9.0, dtype="float32").reshape(3, 3)
    assert np.trace(np.array(m)).item() == onp.trace(m)
