"""Fleet autoscaling + SLO-aware admission (`autoscale` marker).

The tier-1 matrix for ISSUE 18:

- SLOPolicy units: tier classification, tenant-weight parsing, start-
  time-fair-queueing tags (FIFO degeneration, weighted shares, rank
  dominance), service-rate EMA and deadline-infeasibility shedding;
- Autoscaler control loop on FAKE clocks and FAKE replica stats (no
  sleeps, no processes): hysteresis bands, EMA smoothing, cooldown
  anti-flap, chip budget, min-replicas floor, idlest-drain selection,
  prefill<->decode role flips, fault-site behaviour (exception kind
  aborts one tick, soft `drop` inverts the decision under guards),
  decision ring + profiler audit trail;
- admission ladder through the real batcher and decode engine: bulk
  evicted for latency, infeasible deadlines shed typed with an honest
  retry_after, priority dispatch order;
- router: Retry-After computed from shed queue depth / observed service
  rate (deeper queue => larger Retry-After — the satellite regression),
  bulk tier skips the shed retry, runtime set_role re-pools;
- monotonic-clock audit: an NTP wall-clock step must not eject replicas;
- supervisor crash-loop observability ( /v1/stats + Prometheus);
- rollout x session-migration x async-engine composed in one pass;
- the 10x diurnal ramp chaos drill (slow lane).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as onp
import pytest

from mxnet_tpu import faults, profiler, serving
from mxnet_tpu.kvstore.pagestore import PageStoreServer
from mxnet_tpu.serving.replica import demo_affine

pytestmark = [pytest.mark.serving, pytest.mark.autoscale]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ITEM = (4,)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(scope="module")
def lm():
    from mxnet_tpu.models import decoder
    return decoder.decoder_tiny_lm(seed=0, vocab_size=128)


def make_engine(lm, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("max_ctx", 64)
    return serving.DecodeEngine(lm, name="llm", **kw)


def greedy_oracle(lm, prompt, n):
    import jax.numpy as jnp

    from mxnet_tpu.models import decoder
    params, cfg = lm.jax_params(), lm.config
    toks = list(prompt)
    for _ in range(n):
        logits = decoder.full_forward(params, cfg,
                                      jnp.asarray([toks], jnp.int32))
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


# ---------------------------------------------------------------------------
# SLOPolicy: tiers, weights, SFQ tags
# ---------------------------------------------------------------------------
def test_slo_tier_normalization_and_weight_parsing():
    p = serving.SLOPolicy(
        tenant_weights="free=1, pro=4, bad, neg=-2, x=oops",
        default_tier="bulk")
    assert p.weights == {"free": 1.0, "pro": 4.0}  # junk entries dropped
    assert p.normalize_tier(None) == "bulk"
    assert p.normalize_tier("latency") == "latency"
    with pytest.raises(serving.BadRequestError):
        p.normalize_tier("turbo")
    assert p.rank("latency") == 0 and p.rank("bulk") == 1
    assert p.weight("pro") == 4.0
    assert p.weight("unknown") == 1.0 and p.weight(None) == 1.0
    # an unknown default tier falls back to latency, never crashes
    assert serving.SLOPolicy(default_tier="nope").default_tier == "latency"


def test_sfq_degenerates_to_fifo_for_default_traffic():
    """All-default traffic (no tier, no tenant) must order exactly FIFO
    — the regression guard that SLO admission changes nothing for
    existing single-tenant callers."""
    p = serving.SLOPolicy()
    tags = [p.stamp(None, None) for _ in range(6)]
    assert tags == sorted(tags)
    assert all(rank == 0 for rank, _ in tags)
    assert len({v for _, v in tags}) == 6  # strictly increasing: stable


def test_sfq_weighted_fair_share_under_contention():
    p = serving.SLOPolicy(tenant_weights={"pro": 4.0, "free": 1.0})
    reqs = [("pro", p.stamp("latency", "pro")) for _ in range(8)]
    reqs += [("free", p.stamp("latency", "free")) for _ in range(8)]
    order = [t for t, _ in sorted(reqs, key=lambda x: x[1])]
    # weight 4 earns ~4 slots per free slot; free is never starved
    assert order[:10].count("pro") == 8
    assert "free" in order[:2]


def test_bulk_ranks_behind_latency_regardless_of_arrival():
    p = serving.SLOPolicy()
    bulk = p.stamp("bulk", None)
    lat = p.stamp("latency", None)
    assert lat < bulk  # rank dominates vstart


def test_on_dispatch_advances_virtual_server_time():
    p = serving.SLOPolicy()
    tags = [p.stamp(None, "a") for _ in range(3)]
    p.on_dispatch(tags[-1][1])
    # a fresh tenant cannot be stamped into the already-served past
    assert p.stamp(None, "b")[1] >= tags[-1][1]
    p.on_dispatch(0.0)  # never regresses
    assert p.stamp(None, "c")[1] >= tags[-1][1]


def test_service_rate_cold_then_warm_and_infeasibility():
    p = serving.SLOPolicy(ema_alpha=0.5)
    assert p.service_rate() == 0.0
    p.check_deadline(1000, 0.001)  # cold estimator NEVER sheds
    t = 100.0
    for _ in range(5):
        p.observe_served(1, now=t)
        t += 0.1
    assert p.service_rate() == pytest.approx(10.0, rel=0.01)
    assert p.drain_eta_s(20) == pytest.approx(2.0, rel=0.01)
    p.check_deadline(20, 10.0)  # comfortably feasible
    with pytest.raises(serving.DeadlineInfeasibleError) as ei:
        p.check_deadline(20, 0.5)  # 20 queued drain in ~2s, deadline .5s
    assert ei.value.http_status == 503
    assert ei.value.code == "deadline_infeasible"
    assert ei.value.retry_after == pytest.approx(1.5, rel=0.05)


# ---------------------------------------------------------------------------
# Autoscaler: the control loop on fake clocks + fake stats
# ---------------------------------------------------------------------------
def _row(queued=0, active=0, slots=4, kv=0.0, role="mixed",
         routable=True):
    return {"role": role, "routable": routable, "queued": queued,
            "active": active, "slots": slots, "kv_frac": kv}


class _FakeFleet:
    """Scriptable replica-stats source + action recorder — drives the
    Autoscaler with zero processes and zero sleeps."""

    def __init__(self, replicas):
        self.replicas = dict(replicas)
        self.actions = []
        self._next_port = 9100

    def collect(self):
        return {"replicas": {rid: dict(r)
                             for rid, r in self.replicas.items()}}

    def scale_up(self, role):
        rid = "127.0.0.1:%d" % self._next_port
        self._next_port += 1
        self.replicas[rid] = _row(role=role)
        self.actions.append(("up", role))
        return rid

    def scale_down(self, rid):
        self.replicas.pop(rid)
        self.actions.append(("down", rid))
        return 0

    def flip_role(self, rid, role):
        self.replicas[rid]["role"] = role
        self.actions.append(("flip", rid, role))
        return role


def _make_as(fleet, clock, **kw):
    kw.setdefault("ema_alpha", 1.0)   # no smoothing lag unless the
    kw.setdefault("cooldown_s", 0.0)  # test is ABOUT smoothing/cooldown
    kw.setdefault("chip_budget", 4)
    kw.setdefault("min_replicas", 1)
    kw.setdefault("up_queue", 4.0)
    kw.setdefault("down_queue", 0.5)
    kw.setdefault("up_kv", 0.85)
    kw.setdefault("down_kv", 0.3)
    kw.setdefault("interval_ms", 1000.0)
    return serving.Autoscaler(
        clock=clock, collect=fleet.collect, scale_up=fleet.scale_up,
        scale_down=fleet.scale_down, flip_role=fleet.flip_role, **kw)


def test_autoscaler_scales_up_on_queue_band():
    fl = _FakeFleet({"r0": _row(queued=12, active=4)})
    a = _make_as(fl, lambda: 0.0)
    d = a.tick()
    assert d["action"] == "scale_up"
    assert fl.actions == [("up", "mixed")]
    assert d["spawned"] in fl.replicas
    assert a.counters["scale_up"] == 1
    assert d["signals"]["queue_per_replica"] == 12.0


def test_autoscaler_scales_up_on_kv_band():
    fl = _FakeFleet({"r0": _row(kv=0.95)})
    a = _make_as(fl, lambda: 0.0)
    d = a.tick()
    assert d["action"] == "scale_up"
    assert d["reason"].startswith("kv")


def test_autoscaler_holds_inside_hysteresis_bands():
    # queue 2/replica: above the down band, below the up band — and a
    # down-scale needs BOTH signals low (kv alone keeps it alive)
    fl = _FakeFleet({"r0": _row(queued=2)})
    a = _make_as(fl, lambda: 0.0)
    assert a.tick()["action"] == "hold"
    fl2 = _FakeFleet({"r0": _row(queued=0, kv=0.6),
                      "r1": _row(queued=0, kv=0.6)})
    a2 = _make_as(fl2, lambda: 0.0)
    assert a2.tick()["action"] == "hold"  # idle queue but busy KV
    assert a2.counters["holds"] == 1 and fl2.actions == []


def test_autoscaler_ema_absorbs_one_burst():
    """One bursty sample must not trigger an action: the EMA needs the
    signal to PERSIST across ticks before it crosses the band."""
    fl2 = _FakeFleet({"r0": _row(queued=0)})
    a2 = _make_as(fl2, lambda: 0.0, ema_alpha=0.05)
    a2.tick()
    fl2.replicas["r0"] = _row(queued=40)
    assert a2.tick()["action"] == "hold"  # 0.05*40 = 2 < 4: absorbed
    fl2.replicas["r0"] = _row(queued=40)
    for _ in range(40):  # but a SUSTAINED ramp does cross the band
        d = a2.tick()
        if d["action"] == "scale_up":
            break
    assert d["action"] == "scale_up"


def test_autoscaler_cooldown_prevents_flap():
    clk = [0.0]
    fl = _FakeFleet({"r0": _row(queued=40)})
    a = _make_as(fl, lambda: clk[0], cooldown_s=5.0)
    assert a.tick()["action"] == "scale_up"
    fl.replicas = {"r0": _row(queued=40), "r1": _row(queued=40)}
    clk[0] = 2.0  # inside the cooldown: wants to act, must hold
    d = a.tick()
    assert d["action"] == "hold" and "cooldown" in d["reason"]
    clk[0] = 6.0  # past the cooldown: acts again
    assert a.tick()["action"] == "scale_up"
    assert a.counters["scale_up"] == 2 and a.counters["holds"] == 1


def test_autoscaler_respects_chip_budget():
    fl = _FakeFleet({"r0": _row(queued=40), "r1": _row(queued=40)})
    a = _make_as(fl, lambda: 0.0, chip_budget=2)
    d = a.tick()
    assert d["action"] == "hold" and "chip budget" in d["reason"]
    assert fl.actions == []


def test_autoscaler_booting_replicas_count_toward_chip_budget():
    """A spawned-but-not-yet-routable replica still occupies a chip:
    the up band must not keep spawning past the budget while one boots
    (the diurnal-ramp overshoot bug)."""
    fl = _FakeFleet({"r0": _row(queued=40),
                     "b0": _row(routable=False),
                     "b1": _row(routable=False)})
    a = _make_as(fl, lambda: 0.0, chip_budget=3)
    d = a.tick()
    assert d["action"] == "hold" and "chip budget" in d["reason"]
    assert d["signals"]["live"] == 1  # load signals still ignore boots


def test_autoscaler_scale_down_picks_idlest_and_floors_at_min():
    clk = [0.0]
    fl = _FakeFleet({"r0": _row(active=2), "r1": _row(), "r2": _row()})
    a = _make_as(fl, lambda: clk[0], min_replicas=2)
    d = a.tick()
    assert d["action"] == "scale_down"
    assert d["rid"] in ("r1", "r2")  # never the busy one
    assert d["migrated"] == 0
    clk[0] = 10.0
    d2 = a.tick()  # now AT the floor
    assert d2["action"] == "hold" and "min_replicas" in d2["reason"]
    assert len(fl.replicas) == 2


def test_autoscaler_drain_keeps_specialized_pools_nonempty():
    fl = _FakeFleet({"p0": _row(role="prefill"), "m0": _row()})
    a = _make_as(fl, lambda: 0.0)
    d = a.tick()
    # both idle, but the LAST prefill replica is not a drain candidate
    assert d["action"] == "scale_down" and d["rid"] == "m0"


def test_autoscaler_role_flip_rebalances_at_chip_budget():
    fl = _FakeFleet({
        "p0": _row(role="prefill"),
        "p1": _row(role="prefill", active=1),
        "d0": _row(role="decode", queued=10, active=4)})
    a = _make_as(fl, lambda: 0.0, chip_budget=3)
    d = a.tick()
    assert d["action"] == "role_flip"
    assert d["rid"] == "p0" and d["role"] == "decode"  # idlest donor
    assert fl.replicas["p0"]["role"] == "decode"
    assert a.counters["role_flip"] == 1


def test_autoscaler_role_flip_never_empties_a_pool():
    fl = _FakeFleet({"p0": _row(role="prefill"),
                     "d0": _row(role="decode", queued=10, active=4)})
    a = _make_as(fl, lambda: 0.0, chip_budget=2)
    d = a.tick()
    assert d["action"] == "hold"  # only donor is the last prefill
    assert fl.replicas["p0"]["role"] == "prefill"


def test_autoscaler_role_flip_needs_saturation():
    # imbalance ratio alone is not enough: the heavy pool must be
    # saturated (load >= 1 slot-equivalent) before a flip is worth it
    # (signals sit mid-band so neither scale direction preempts)
    fl = _FakeFleet({"p0": _row(role="prefill"),
                     "p1": _row(role="prefill"),
                     "d0": _row(role="decode", queued=2, active=1)})
    a = _make_as(fl, lambda: 0.0, chip_budget=3)
    d = a.tick()
    assert d["action"] == "hold" and "hysteresis" in d["reason"]


def test_autoscaler_fault_exception_aborts_one_tick_only():
    fl = _FakeFleet({"r0": _row(queued=40)})
    a = _make_as(fl, lambda: 0.0)
    with faults.inject("autoscale.decide", "error", n=1, max_trips=1):
        d = a.tick()
    assert d["action"] == "error" and "decide fault" in d["reason"]
    assert a.counters["errors"] == 1 and fl.actions == []
    assert a.tick()["action"] == "scale_up"  # next tick recovers


def test_autoscaler_fault_drop_inverts_decision_with_guards():
    # the chaos mis-scaling drill: soft `drop` forces the WRONG
    # direction — but the safety guards still clamp it
    fl = _FakeFleet({"r0": _row(queued=40), "r1": _row(queued=40)})
    a = _make_as(fl, lambda: 0.0)
    with faults.inject("autoscale.decide", "drop", n=1):
        d = a.tick()
    assert d["action"] == "scale_down"
    assert "fault-inverted" in d["reason"]
    assert len(fl.replicas) == 1
    # at min_replicas the inverted drain is refused outright
    fl2 = _FakeFleet({"r0": _row(queued=40)})
    a2 = _make_as(fl2, lambda: 0.0)
    with faults.inject("autoscale.decide", "drop", n=1):
        d2 = a2.tick()
    assert d2["action"] == "hold" and "refused" in d2["reason"]
    assert len(fl2.replicas) == 1


def test_autoscaler_collect_and_hook_failures_are_typed_errors():
    a = _make_as(_FakeFleet({}), lambda: 0.0)
    a._collect = lambda: (_ for _ in ()).throw(OSError("replica gone"))
    d = a.tick()
    assert d["action"] == "error" and "collect failed" in d["reason"]
    fl = _FakeFleet({"r0": _row(queued=40)})
    a2 = _make_as(fl, lambda: 0.0)
    a2._scale_up = lambda role: (_ for _ in ()).throw(
        RuntimeError("spawn refused"))
    d2 = a2.tick()
    assert d2["action"] == "error" and "scale_up failed" in d2["reason"]
    assert a2.counters["errors"] == 1 and a2.counters["scale_up"] == 0


def test_autoscaler_decisions_ring_and_profiler_audit():
    profiler.reset_stats()
    clk = [0.0]
    fl = _FakeFleet({"r0": _row(queued=40)})
    a = _make_as(fl, lambda: clk[0])
    a.tick()                              # scale_up
    fl.replicas = {rid: _row(queued=2) for rid in fl.replicas}
    clk[0] = 10.0
    a.tick()                              # hold
    snap = a.snapshot()
    assert [d["action"] for d in snap["decisions"]] == ["scale_up",
                                                        "hold"]
    assert snap["last_decision"]["action"] == "hold"
    assert snap["counters"]["ticks"] == 2
    assert snap["signals"]["live"] == 2
    assert snap["config"]["chip_budget"] == 4
    # every decision lands in the profiler fleet table; non-holds are
    # also first-class fleet events
    agg = profiler.aggregate_stats()
    assert agg["fleet"]["autoscale.scale_up"]["count"] == 1
    assert agg["fleet"]["autoscale.hold"]["count"] == 1
    assert agg["events"]["fleet.autoscale_scale_up"] == 1
    assert "fleet.autoscale_hold" not in agg["events"]


def test_autoscaler_background_thread_runs_and_stops():
    fl = _FakeFleet({"r0": _row(queued=2)})
    a = _make_as(fl, time.monotonic, interval_ms=5.0)
    a.start()
    a.start()  # idempotent
    deadline = time.monotonic() + 5.0
    while a.counters["ticks"] < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    a.stop()
    assert a.counters["ticks"] >= 3
    n = a.counters["ticks"]
    time.sleep(0.05)
    assert a.counters["ticks"] == n  # stopped means stopped


def test_new_fault_sites_are_registered():
    assert "autoscale.decide" in faults.KNOWN_SITES
    assert "replica.spawn" in faults.KNOWN_SITES


# ---------------------------------------------------------------------------
# admission ladder through the real batcher
# ---------------------------------------------------------------------------
def _blocked_batcher(max_queue_depth=2):
    """Registry + batcher whose model fn blocks on a gate, so queued
    requests stay queued deterministically."""
    order = []
    gate = threading.Event()

    def fn(x):
        gate.wait(10)
        order.append(float(onp.asarray(x)[0][0]))
        return x

    reg = serving.ModelRegistry()
    reg.load("m", fn, item_shape=ITEM, max_batch_size=1, warmup=False)
    b = serving.DynamicBatcher(reg, flush_ms=1,
                               max_queue_depth=max_queue_depth)
    return b, gate, order


def _item(v=0.0):
    return onp.full(ITEM, v, dtype="float32")


def _wait_drained(b, model="m", timeout=5.0):
    deadline = time.monotonic() + timeout
    while b.queue_depth(model) and time.monotonic() < deadline:
        time.sleep(0.002)
    assert b.queue_depth(model) == 0


def test_batcher_bulk_evicted_to_admit_latency():
    b, gate, _ = _blocked_batcher(max_queue_depth=2)
    try:
        f0 = b.submit("m", _item())        # worker grabs it, blocks
        _wait_drained(b)
        fb1 = b.submit("m", _item(1.0), tier="bulk")
        fb2 = b.submit("m", _item(2.0), tier="bulk")
        # a BULK arrival at a full queue sheds itself, evicting no one
        with pytest.raises(serving.QueueFullError):
            b.submit("m", _item(9.0), tier="bulk")
        # a LATENCY arrival evicts the newest bulk request instead
        fl_ = b.submit("m", _item(3.0), tier="latency")
        with pytest.raises(serving.QueueFullError) as ei:
            fb2.result(5)
        assert ei.value.queued is not None  # honest depth in the 503
        gate.set()
        for f in (f0, fb1, fl_):
            f.result(10)
        ctr = b.metrics.snapshot()["models"]["m"]["counters"]
        assert ctr["bulk_evicted_total"] == 1
        assert ctr["shed_total"] == 2  # the self-shed + the eviction
    finally:
        gate.set()
        b.stop()


def test_batcher_latency_dispatches_before_queued_bulk():
    b, gate, order = _blocked_batcher(max_queue_depth=16)
    try:
        f0 = b.submit("m", _item(0.0))
        _wait_drained(b)
        futs = [b.submit("m", _item(1.0), tier="bulk"),
                b.submit("m", _item(2.0), tier="bulk"),
                b.submit("m", _item(3.0), tier="latency")]
        gate.set()
        f0.result(10)
        for f in futs:
            f.result(10)
        # head-of-line: the latency request jumped both queued bulks
        assert order[0] == 0.0 and order[1] == 3.0
        assert sorted(order[2:]) == [1.0, 2.0]
    finally:
        gate.set()
        b.stop()


def test_batcher_infeasible_deadline_sheds_with_drain_estimate():
    b, gate, _ = _blocked_batcher(max_queue_depth=64)
    try:
        t = 0.0
        for _ in range(5):  # prime the estimator at 1 req/s (fed clock)
            b.slo.observe_served(1, now=t)
            t += 1.0
        f0 = b.submit("m", _item())
        _wait_drained(b)
        futs = [b.submit("m", _item()) for _ in range(10)]
        with pytest.raises(serving.DeadlineInfeasibleError) as ei:
            b.submit("m", _item(), deadline_ms=500.0)  # ~10s of queue
        assert ei.value.retry_after >= 5.0  # honest drain estimate
        ctr = b.metrics.snapshot()["models"]["m"]["counters"]
        assert ctr["infeasible_shed_total"] == 1
        # a generous deadline still admits
        f_ok = b.submit("m", _item(), deadline_ms=60000.0)
        gate.set()
        f0.result(10)
        f_ok.result(20)
        for f in futs:
            f.result(20)
    finally:
        gate.set()
        b.stop()


# ---------------------------------------------------------------------------
# admission ladder through the real decode engine
# ---------------------------------------------------------------------------
def test_engine_set_role_runtime_and_slo_stats(lm):
    eng = make_engine(lm)
    try:
        assert eng.set_role("prefill") == "mixed"
        st = eng.stats()
        assert st["role"] == "prefill"
        assert "service_rate" in st["slo"]
        assert eng.set_role("mixed") == "prefill"
        with pytest.raises(serving.BadRequestError):
            eng.set_role("turbo")
    finally:
        eng.stop()


def test_engine_bulk_eviction_and_priority_order(lm):
    eng = make_engine(lm)
    eng.max_queue_depth = 2
    eng._ensure_worker_locked = lambda: None  # hold requests in queue
    with pytest.raises(serving.BadRequestError):
        eng.submit([1, 2], 2, tier="turbo")
    fb1 = eng.submit([1, 2], 2, tier="bulk")
    fb2 = eng.submit([3, 4], 2, tier="bulk")
    with pytest.raises(serving.QueueFullError):
        eng.submit([5, 6], 2, tier="bulk")  # bulk cannot evict bulk
    eng.submit([5, 6], 2, tier="latency")   # evicts the NEWEST bulk
    with pytest.raises(serving.QueueFullError) as ei:
        fb2.result(5)
    assert ei.value.queued == 1
    assert [r.tier for r in eng._queue] == ["latency", "bulk"]
    assert not fb1.done()
    ctr = eng.metrics.snapshot()["models"]["llm"]["counters"]
    assert ctr["bulk_evicted_total"] == 1
    eng.stop()


def test_engine_infeasible_deadline_sheds_typed(lm):
    eng = make_engine(lm)
    eng._ensure_worker_locked = lambda: None
    t = 0.0
    for _ in range(5):
        eng.slo.observe_served(1, now=t)
        t += 1.0  # 1 generation/s
    for _ in range(5):
        eng.submit([1, 2], 2)
    with pytest.raises(serving.DeadlineInfeasibleError) as ei:
        eng.submit([1, 2], 2, deadline_ms=1000.0)  # 5 ahead at 1/s
    assert ei.value.retry_after >= 3.0
    ctr = eng.metrics.snapshot()["models"]["llm"]["counters"]
    assert ctr["infeasible_shed_total"] == 1
    eng.stop()


# ---------------------------------------------------------------------------
# router: honest Retry-After + tier-aware dispatch + runtime re-pooling
# ---------------------------------------------------------------------------
def _shed_server(queued):
    """A replica that always sheds, reporting its queue depth."""

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            if n:
                self.rfile.read(n)
            body = json.dumps({"error": "full", "code": "queue_full",
                               "queued": queued}).encode()
            self.send_response(503)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def _shed_addrs(servers):
    return ["127.0.0.1:%d" % s.server_address[1] for s in servers]


def test_router_retry_after_grows_with_shed_queue_depth():
    """The satellite regression: Retry-After is computed from the
    aggregate shed queue depth / observed service rate — a deeper
    backlog tells clients to back off LONGER (the old code said 'try
    again in probe_s*2' no matter what)."""

    def run(queued, rate):
        servers = [_shed_server(queued) for _ in range(2)]
        router = serving.Router(_shed_addrs(servers), probe_ms=0)
        router.metrics._rate = rate
        try:
            with pytest.raises(serving.QueueFullError) as ei:
                router.dispatch("/v1/models/m:predict",
                                {"instances": [[0.0] * 4]})
            return ei.value
        finally:
            router.stop()
            for s in servers:
                s.shutdown()
                s.server_close()

    shallow = run(5, rate=10.0)    # 2 replicas shed: 10 queued total
    deep = run(200, rate=10.0)     # 400 queued total
    assert shallow.queued == 10 and deep.queued == 400
    assert shallow.retry_after == pytest.approx(1.0, rel=0.01)
    assert deep.retry_after == pytest.approx(40.0, rel=0.01)
    assert deep.retry_after > shallow.retry_after
    # cold rate estimator: falls back to the bounded probe heuristic
    cold = run(200, rate=0.0)
    assert 0.1 <= cold.retry_after <= 1.0
    # the estimate is clamped to a sane ceiling
    assert run(100000, rate=0.1).retry_after == 60.0


def test_router_bulk_tier_skips_the_shed_retry():
    shed = _shed_server(7)
    reg = serving.ModelRegistry()
    reg.load("m", demo_affine(scale=2.0), item_shape=ITEM,
             max_batch_size=4, warmup=False)
    good = serving.ModelServer(reg, flush_ms=2)
    good.start()
    body = {"instances": [[0.0] * 4]}
    try:
        # latency (default) tier: the shed retries onto the healthy
        # replica and succeeds
        r1 = serving.Router(_shed_addrs([shed])
                            + ["127.0.0.1:%d" % good.port], probe_ms=0)
        hits = 0
        for _ in range(8):
            try:
                status, _ = r1.dispatch("/v1/models/m:predict", body)
                assert status == 200
                hits += 1
            except serving.QueueFullError:
                pass  # picked the healthy replica twice: no shed seen
        assert hits == 8  # every dispatch that shed got its retry
        r1.stop()
        # bulk tier: first shed propagates — the retry capacity belongs
        # to the latency tier
        r2 = serving.Router(_shed_addrs([shed]), probe_ms=0)
        with pytest.raises(serving.QueueFullError) as ei:
            r2.dispatch("/v1/models/m:predict", body, tier="bulk")
        assert ei.value.queued == 7
        assert r2.metrics.counters["retries_total"] == 0
        r2.stop()
    finally:
        good.stop()
        shed.shutdown()
        shed.server_close()


def test_router_set_role_repools_and_admin_endpoint(lm):
    eng = make_engine(lm)
    srv = serving.ModelServer(serving.ModelRegistry(), admin=True)
    srv.start()
    srv.attach_engine("llm", eng)
    rid = "127.0.0.1:%d" % srv.port
    router = serving.Router([rid], probe_ms=0)
    rs = serving.RouterServer(router)
    rs.start()
    try:
        status, doc = rs._handle_post(
            "/v1/admin/set_role",
            json.dumps({"replica": rid, "role": "decode"}).encode())
        assert status == 200 and doc["ok"]
        assert doc["previous"] == "mixed"
        assert doc["engines"] == {"llm": "mixed"}
        assert router.states()[rid]["role"] == "decode"
        assert eng.role == "decode"  # engine and router moved together
        with pytest.raises(serving.ServingError):
            rs._handle_post("/v1/admin/set_role",
                            json.dumps({"role": "prefill"}).encode())
        with pytest.raises(serving.ModelNotFoundError):
            rs._handle_post(
                "/v1/admin/set_role",
                json.dumps({"replica": "1.2.3.4:1",
                            "role": "prefill"}).encode())
    finally:
        rs.stop()
        srv.stop()
        eng.stop()


# ---------------------------------------------------------------------------
# monotonic clocks: an NTP step must not eject anyone
# ---------------------------------------------------------------------------
def test_wall_clock_step_does_not_eject_replicas(monkeypatch):
    """Jump time.time() an hour forward mid-traffic: every fleet timer
    (probe cadence, strike backoff, eject/readmit) runs on monotonic
    clocks, so nothing is ejected and traffic keeps flowing."""
    reg = serving.ModelRegistry()
    reg.load("m", demo_affine(scale=2.0), item_shape=ITEM,
             max_batch_size=4, warmup=False)
    servers = []
    for _ in range(2):
        s = serving.ModelServer(reg, flush_ms=2)
        s.start()
        servers.append(s)
    router = serving.Router(["127.0.0.1:%d" % s.port for s in servers],
                            probe_ms=30)
    body = {"instances": [[1.0] * 4]}
    try:
        status, _ = router.dispatch("/v1/models/m:predict", body)
        assert status == 200
        real_time = time.time
        monkeypatch.setattr(time, "time",
                            lambda: real_time() + 3600.0)
        time.sleep(0.15)  # several probe cycles under the skewed clock
        for _ in range(6):
            status, _ = router.dispatch("/v1/models/m:predict", body)
            assert status == 200
        for rid, st in router.states().items():
            assert st["state"] == "healthy" and st["ready"], (rid, st)
            assert st["strikes"] == 0
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_fleet_timers_never_read_wall_clock():
    """Source audit: the fleet's timing logic (probes, strikes, backoff,
    batching deadlines, autoscale cooldowns) must be wall-clock-free —
    time.time() is only legal as a human-facing label elsewhere."""
    src_dir = os.path.join(REPO, "mxnet_tpu", "serving")
    for mod in ("router.py", "supervisor.py", "batcher.py",
                "autoscale.py", "generate.py", "fleet.py", "server.py"):
        with open(os.path.join(src_dir, mod)) as f:
            assert "time.time(" not in f.read(), (
                "%s uses wall-clock time in fleet logic" % mod)


# ---------------------------------------------------------------------------
# observability: supervisor crash-loop state + autoscale at the router
# ---------------------------------------------------------------------------
def test_supervisor_states_expose_crash_loop_internals():
    from mxnet_tpu.serving.supervisor import ReplicaSupervisor
    sup = ReplicaSupervisor({"models": []}, replicas=2,
                            restart_budget=5, restart_window_s=60.0)
    st = sup.states()
    assert set(st) == {"r0", "r1"}
    for d in st.values():
        assert d["restart_budget"] == 5
        assert d["restart_budget_remaining"] == 5
        assert d["restarts_in_window"] == 0
        assert d["backoff_stage"] == 0
        assert d["next_restart_in_s"] == 0.0
    # simulate a crash-looping replica
    r = sup.replicas[0]
    now = time.monotonic()
    r.restart_times.extend([now - 100.0, now - 5.0, now - 1.0])
    r.consecutive_crashes = 2
    r.next_restart = now + 0.8
    d = sup.states()["r0"]
    assert d["restarts_in_window"] == 2  # the -100s one aged out
    assert d["restart_budget_remaining"] == 3
    assert d["backoff_stage"] == 2
    assert 0.0 < d["next_restart_in_s"] <= 0.8


def test_router_stats_and_prometheus_carry_fleet_control_state():
    from mxnet_tpu.serving.supervisor import ReplicaSupervisor
    reg = serving.ModelRegistry()
    reg.load("m", demo_affine(scale=2.0), item_shape=ITEM,
             max_batch_size=4, warmup=False)
    srv = serving.ModelServer(reg, flush_ms=2)
    srv.start()
    router = serving.Router(["127.0.0.1:%d" % srv.port], probe_ms=0)
    sup = ReplicaSupervisor({"models": []}, replicas=1)
    fl = _FakeFleet({"r0": _row(queued=40)})
    scaler = _make_as(fl, lambda: 0.0)
    scaler.tick()
    rs = serving.RouterServer(router, supervisor=sup, autoscaler=scaler)
    try:
        status, snap = rs._handle_get("/v1/stats")
        assert status == 200
        assert snap["supervisor"]["r0"]["restart_budget_remaining"] >= 0
        assert snap["autoscale"]["counters"]["scale_up"] == 1
        assert snap["autoscale"]["last_decision"]["action"] == "scale_up"
        text = rs._prometheus_text()
        assert "mxtpu_fleet_service_rate" in text
        assert "mxtpu_fleet_replica_restart_budget_remaining" in text
        assert "mxtpu_fleet_replica_failed" in text
        assert "mxtpu_fleet_autoscale_scale_up_total 1" in text
        # `live` is the signal the decision SAW (pre-spawn): 1 replica
        assert "mxtpu_fleet_autoscale_replicas_live 1" in text
        assert "mxtpu_fleet_autoscale_chip_budget 4" in text
    finally:
        router.stop()
        srv.stop()


# ---------------------------------------------------------------------------
# ServingFleet autoscaler hooks (in-process replicas; no subprocesses)
# ---------------------------------------------------------------------------
@pytest.fixture()
def store():
    s = PageStoreServer()
    s.start()
    yield s
    s.stop()


def _fleet_shell(replica_servers, replicas=2):
    """A ServingFleet wired to IN-PROCESS replicas: supervisor built but
    never started, router pointed at live ModelServers — enough to
    exercise the autoscale hooks without subprocess spawns."""
    fleet = serving.ServingFleet({"models": []}, replicas=replicas)
    fleet.router = serving.Router(
        ["127.0.0.1:%d" % s.port for s in replica_servers], probe_ms=0)
    return fleet


def test_fleet_collect_aggregates_replica_signals(lm):
    eng = make_engine(lm)
    srv = serving.ModelServer(serving.ModelRegistry(), admin=True)
    srv.start()
    srv.attach_engine("llm", eng)
    fleet = _fleet_shell([srv])
    try:
        eng.submit([1, 2, 3], 3).result(30)
        stats = fleet._autoscale_collect()
        rid = "127.0.0.1:%d" % srv.port
        row = stats["replicas"][rid]
        assert row["routable"] and row["role"] == "mixed"
        assert row["slots"] == 4 and row["queued"] == 0
        assert 0.0 <= row["kv_frac"] <= 1.0
        # a drained replica reports unroutable and is not polled
        fleet.router.set_drain(rid, True)
        row2 = fleet._autoscale_collect()["replicas"][rid]
        assert not row2["routable"] and row2["slots"] == 0
    finally:
        fleet.router.stop()
        srv.stop()
        eng.stop()


def test_fleet_scale_up_hook_registers_unroutable_replica():
    srv = serving.ModelServer(serving.ModelRegistry(), admin=True)
    srv.start()
    fleet = _fleet_shell([srv], replicas=1)
    try:
        n0 = len(fleet.supervisor.replicas)
        addr = fleet._autoscale_up("decode")
        st = fleet.router.states()[addr]
        assert st["role"] == "decode"
        assert not st["ready"]  # unroutable until /readyz says so
        assert len(fleet.supervisor.replicas) == n0 + 1
        new = fleet.supervisor.replicas[-1]
        assert fleet.supervisor.env_by_rid[new.rid] == {
            "MXNET_GEN_ROLE": "decode"}
        with faults.inject("replica.spawn", "error", n=1):
            with pytest.raises(Exception):
                fleet._autoscale_up("mixed")
    finally:
        fleet.router.stop()
        srv.stop()


def test_fleet_scale_down_drains_by_migration_not_reset(lm, store):
    """The drain path of a scale-down: every parked session rides the
    page store to a survivor, bit-identically — never reset."""
    engines, servers = [], []
    for _ in range(2):
        e = make_engine(lm, pagestore=store.address)
        s = serving.ModelServer(serving.ModelRegistry(), admin=True)
        s.start()
        s.attach_engine("llm", e)
        engines.append(e)
        servers.append(s)
    fleet = _fleet_shell(servers)
    rid0 = "127.0.0.1:%d" % servers[0].port
    prompt = [5, 4, 3, 2, 1]
    try:
        r1 = engines[0].submit(prompt, 4, session="ride").result(30)
        migrated = fleet._autoscale_down(rid0)
        assert migrated == 1
        assert rid0 not in fleet.router.replica_ids()
        hist = prompt + r1["tokens"]
        r2 = engines[1].submit([8], 4, session="ride",
                               resume=True).result(30)
        assert r2["tokens"] == greedy_oracle(lm, hist + [8], 4)
    finally:
        fleet.router.stop()
        for s in servers:
            s.stop()
        for e in engines:
            e.stop()


def test_fleet_flip_role_moves_engine_router_and_restart_env(lm):
    eng = make_engine(lm)
    srv = serving.ModelServer(serving.ModelRegistry(), admin=True)
    srv.start()
    srv.attach_engine("llm", eng)
    fleet = _fleet_shell([srv], replicas=1)
    rid = "127.0.0.1:%d" % srv.port
    # align the (unstarted) supervisor's slot with the live replica so
    # the hook's restart-env stamping is observable
    fleet.supervisor.replicas[0].port = srv.port
    try:
        fleet._autoscale_flip(rid, "prefill")
        assert eng.role == "prefill"
        assert fleet.router.states()[rid]["role"] == "prefill"
        srid = fleet.supervisor.replicas[0].rid
        assert fleet.supervisor.env_by_rid[srid] == {
            "MXNET_GEN_ROLE": "prefill"}
        fleet._autoscale_flip(rid, "mixed")  # flipping back clears it
        assert fleet.supervisor.env_by_rid[srid] == {}
    finally:
        fleet.router.stop()
        srv.stop()
        eng.stop()


def test_serving_fleet_accepts_autoscale_config():
    fleet = serving.ServingFleet({"models": []}, replicas=1,
                                 autoscale={"chip_budget": 2,
                                            "interval_ms": 50.0})
    assert fleet.autoscaler is None  # built at start(), stopped at stop
    assert fleet._autoscale_cfg == {"chip_budget": 2,
                                    "interval_ms": 50.0}
    assert fleet.status()["autoscale"] is None


# ---------------------------------------------------------------------------
# composed: rollout x session migration x async engine, one pass
# ---------------------------------------------------------------------------
def test_rollout_migration_async_composed(lm, store, monkeypatch):
    """Satellite 4: one pass through rollout WITH parked sessions WITH
    the async decode engine forced on — the three features compose, the
    session survives the rollout bit-identically, zero resets."""
    monkeypatch.setenv("MXNET_GEN_ASYNC", "1")
    engines, servers = [], []
    for _ in range(2):
        e = make_engine(lm, pagestore=store.address, async_decode=True)
        s = serving.ModelServer(serving.ModelRegistry(), admin=True)
        s.start()
        s.attach_engine("llm", e)
        engines.append(e)
        servers.append(s)
    router = serving.Router(["127.0.0.1:%d" % s.port for s in servers],
                            probe_ms=0)
    rs = serving.RouterServer(router)
    rs.start()
    prompt = [7, 6, 5, 4, 3, 2]
    try:
        assert engines[0].stats()["async"]["enabled"]
        cli = serving.ServingClient(*rs.address, timeout=60)
        r1 = cli.generate("llm", prompt, max_tokens=4, session="ride")
        from mxnet_tpu.serving.fleet import rollout
        report = rollout(router, {
            "name": "llm",
            "builder": "mxnet_tpu.models.decoder:decoder_tiny_lm",
            "kwargs": {"seed": 0, "vocab_size": 128},
            "generate": {"slots": 4, "page_size": 8, "prefill_chunk": 8,
                         "max_ctx": 64, "pagestore": store.address}})
        assert not report["aborted"]
        # the parked session MIGRATED through the rollout (each drained
        # replica pushed its sessions before the engine swap)
        assert sum(r["migrated_sessions"]
                   for r in report["replicas"]) >= 1
        # the swapped-in engines still run async pipelining
        for s in servers:
            eng = s.batcher._engines["llm"]
            assert eng.stats()["async"]["enabled"]
        # resume the pre-rollout session: bit-identical continuation,
        # no SessionResetError anywhere
        hist = list(prompt) + list(r1["tokens"])
        r2 = cli.generate("llm", [9], max_tokens=4, session="ride",
                          resume=True)
        assert r2["tokens"] == greedy_oracle(lm, hist + [9], 4)
        cli.close()
    finally:
        rs.stop()
        for s in servers:
            s.stop()
        for e in engines:
            e.stop()


# ---------------------------------------------------------------------------
# chaos acceptance: the 10x diurnal ramp (slow lane)
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_chaos_ramp_10x_diurnal():
    """The ISSUE acceptance: a 10x two-tier, three-tenant traffic ramp
    against an autoscaling fleet — latency-tier p99 bounded, bulk shed
    first, zero session resets, replica count tracks load under the
    chip budget, every decision auditable."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos.py"),
         "--scenario", "ramp"],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO)
    sys.stdout.write(out.stdout[-4000:])
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    assert "chaos: PASS" in out.stdout
