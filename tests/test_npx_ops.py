"""npx NN-op tests vs NumPy references (reference analog:
tests/python/unittest/test_operator.py for nn ops)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np, npx, autograd


def test_softmax_matches_numpy():
    x = onp.random.RandomState(0).randn(3, 5).astype("float32")
    out = npx.softmax(np.array(x)).asnumpy()
    e = onp.exp(x - x.max(-1, keepdims=True))
    onp.testing.assert_allclose(out, e / e.sum(-1, keepdims=True), rtol=1e-5)
    out0 = npx.softmax(np.array(x), axis=0).asnumpy()
    e0 = onp.exp(x - x.max(0, keepdims=True))
    onp.testing.assert_allclose(out0, e0 / e0.sum(0, keepdims=True), rtol=1e-5)


def test_softmax_with_length():
    x = onp.random.RandomState(0).randn(2, 4).astype("float32")
    length = np.array([2, 3], dtype="int32")
    out = npx.softmax(np.array(x), length=length, use_length=True).asnumpy()
    assert out[0, 2] == 0 and out[0, 3] == 0 and out[1, 3] == 0
    onp.testing.assert_allclose(out.sum(-1), [1.0, 1.0], rtol=1e-5)


def test_masked_softmax():
    x = onp.random.RandomState(0).randn(2, 4).astype("float32")
    mask = onp.array([[1, 1, 0, 0], [1, 1, 1, 0]], bool)
    out = npx.masked_softmax(np.array(x), np.array(mask)).asnumpy()
    assert (out[~mask] == 0).all()
    onp.testing.assert_allclose(out.sum(-1), [1.0, 1.0], rtol=1e-5)


def test_log_softmax_safe_accumulation():
    # large fp16-range values shouldn't overflow (MXNET_SAFE_ACCUMULATION)
    x = np.array(onp.array([[10000.0, 10001.0]], "float32"))
    out = npx.log_softmax(x).asnumpy()
    assert onp.isfinite(out).all()


def test_one_hot_topk_pick():
    oh = npx.one_hot(np.array([0, 2], dtype="int32"), 4).asnumpy()
    onp.testing.assert_array_equal(oh, [[1, 0, 0, 0], [0, 0, 1, 0]])

    x = onp.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]], "float32")
    idx = npx.topk(np.array(x), k=2, ret_typ="indices").asnumpy()
    onp.testing.assert_array_equal(idx, [[0, 2], [1, 2]])
    vals, idx2 = npx.topk(np.array(x), k=1, ret_typ="both")
    onp.testing.assert_array_equal(vals.asnumpy(), [[3.0], [5.0]])
    asc = npx.topk(np.array(x), k=1, is_ascend=True, ret_typ="value").asnumpy()
    onp.testing.assert_array_equal(asc, [[1.0], [0.0]])

    picked = npx.pick(np.array(x), np.array([2, 0])).asnumpy()
    onp.testing.assert_array_equal(picked, [2.0, 0.0])


def test_gather_scatter_nd():
    data = np.array(onp.arange(12.0, dtype="float32").reshape(3, 4))
    indices = np.array([[0, 2], [1, 3]], dtype="int32")  # rows then cols
    out = npx.gather_nd(data, indices).asnumpy()
    onp.testing.assert_array_equal(out, [1.0, 11.0])
    sc = npx.scatter_nd(np.array([5.0, 6.0]), indices, (3, 4)).asnumpy()
    assert sc[0, 1] == 5.0 and sc[2, 3] == 6.0


def test_sequence_ops():
    # data (L, B, D)
    data = onp.arange(24.0, dtype="float32").reshape(4, 2, 3)
    length = np.array([2, 3], dtype="int32")
    masked = npx.sequence_mask(np.array(data), length,
                               use_sequence_length=True, value=-1).asnumpy()
    assert (masked[2:, 0] == -1).all()
    assert (masked[3:, 1] == -1).all()
    onp.testing.assert_array_equal(masked[:2], data[:2])

    last = npx.sequence_last(np.array(data), length,
                             use_sequence_length=True).asnumpy()
    onp.testing.assert_array_equal(last[0], data[1, 0])
    onp.testing.assert_array_equal(last[1], data[2, 1])

    rev = npx.sequence_reverse(np.array(data), length,
                               use_sequence_length=True).asnumpy()
    onp.testing.assert_array_equal(rev[0, 0], data[1, 0])
    onp.testing.assert_array_equal(rev[1, 0], data[0, 0])
    onp.testing.assert_array_equal(rev[2, 0], data[2, 0])  # beyond len kept


def test_batch_dot():
    rng = onp.random.RandomState(0)
    a = rng.randn(2, 3, 4).astype("float32")
    b = rng.randn(2, 4, 5).astype("float32")
    out = npx.batch_dot(np.array(a), np.array(b)).asnumpy()
    onp.testing.assert_allclose(out, a @ b, rtol=1e-5)
    out_t = npx.batch_dot(np.array(a), np.array(b.transpose(0, 2, 1)),
                          transpose_b=True).asnumpy()
    onp.testing.assert_allclose(out_t, a @ b, rtol=1e-5)


def test_arange_like_reshape_like():
    x = np.zeros((2, 3))
    al = npx.arange_like(x).asnumpy()
    onp.testing.assert_array_equal(al, onp.arange(6.0).reshape(2, 3))
    al0 = npx.arange_like(x, axis=0).asnumpy()
    onp.testing.assert_array_equal(al0, [0.0, 1.0])
    r = npx.reshape_like(np.arange(6.0), x).asnumpy()
    assert r.shape == (2, 3)


def test_smooth_l1():
    x = np.array([-2.0, -0.5, 0.0, 0.5, 2.0])
    out = npx.smooth_l1(x, scalar=1.0).asnumpy()
    onp.testing.assert_allclose(out, [1.5, 0.125, 0.0, 0.125, 1.5], rtol=1e-6)


def test_all_finite():
    assert bool(npx.all_finite(np.ones((3,)), np.zeros((2,))))
    assert not bool(npx.all_finite(np.array([1.0, onp.inf])))
    assert not bool(npx.all_finite(np.array([onp.nan])))


def test_embedding_op():
    w = np.array(onp.eye(4, 3, dtype="float32"))
    out = npx.embedding(np.array([1, 3], dtype="int32"), w).asnumpy()
    onp.testing.assert_array_equal(out[0], [0, 1, 0])


def test_activation_grads():
    for act in ["relu", "sigmoid", "tanh", "softrelu", "gelu"]:
        x = np.array([0.3, -0.7])
        x.attach_grad()
        with autograd.record():
            y = npx.activation(x, act).sum()
        y.backward()
        assert onp.isfinite(x.grad.asnumpy()).all()


def test_layer_norm_op_matches_numpy():
    x = onp.random.RandomState(0).randn(4, 6).astype("float32")
    g = onp.random.RandomState(1).rand(6).astype("float32")
    b = onp.random.RandomState(2).rand(6).astype("float32")
    out = npx.layer_norm(np.array(x), np.array(g), np.array(b)).asnumpy()
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    expect = (x - mu) / onp.sqrt(var + 1e-5) * g + b
    onp.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_lrn():
    x = onp.abs(onp.random.RandomState(0).randn(1, 4, 3, 3)).astype("float32")
    out = npx.lrn(np.array(x), nsize=3).asnumpy()
    assert out.shape == x.shape
    assert (out <= x + 1e-6).all()  # LRN divides by >= 1


def test_l2_normalization():
    x = onp.random.RandomState(0).randn(2, 5).astype("float32")
    out = npx.l2_normalization(np.array(x), mode="instance").asnumpy()
    onp.testing.assert_allclose((out ** 2).sum(-1), [1.0, 1.0], rtol=1e-4)


def test_save_load(tmp_path):
    f = str(tmp_path / "arrs.npz")
    npx.save(f, {"a": np.ones((2,)), "b": np.zeros((3,))})
    loaded = npx.load(f)
    onp.testing.assert_array_equal(loaded["a"].asnumpy(), [1, 1])
    onp.testing.assert_array_equal(loaded["b"].asnumpy(), [0, 0, 0])


def test_control_flow():
    # npx.foreach
    def body(x, states):
        return x * 2, [states[0] + x.sum()]

    data = np.array(onp.arange(6.0, dtype="float32").reshape(3, 2))
    outs, states = npx.foreach(body, data, [np.array(0.0)])
    onp.testing.assert_array_equal(outs.asnumpy(), data.asnumpy() * 2)
    assert float(states[0]) == 15.0

    # npx.while_loop
    def cond(i, s):
        return i < 3

    def func(i, s):
        return s * 2, [i + 1, s * 2]

    outs, (i, s) = npx.while_loop(cond, func, [np.array(0), np.array(1.0)],
                                  max_iterations=10)
    assert float(s) == 8.0

    # npx.cond
    r = npx.cond(np.array(True), lambda: np.array(1.0), lambda: np.array(2.0))
    assert float(r) == 1.0


def test_interleaved_matmul_attention():
    """Fused attention projections vs explicit einsum reference
    (src/operator/contrib/transformer.cc parity)."""
    L, B, H, D = 5, 2, 2, 4
    rng = onp.random.RandomState(0)
    qkv = rng.randn(L, B, H * 3 * D).astype("float32")
    att = npx.interleaved_matmul_selfatt_qk(np.array(qkv), heads=H)
    assert att.shape == (B * H, L, L)
    x = qkv.reshape(L, B, H, 3, D)
    q, k = x[:, :, :, 0], x[:, :, :, 1]
    expect = onp.einsum("lbhd,mbhd->bhlm", q / onp.sqrt(D), k).reshape(B * H, L, L)
    onp.testing.assert_allclose(att.asnumpy(), expect, rtol=1e-4, atol=1e-5)

    probs = npx.softmax(att, axis=-1)
    out = npx.interleaved_matmul_selfatt_valatt(np.array(qkv), probs, heads=H)
    assert out.shape == (L, B, H * D)
    v = x[:, :, :, 2]
    p = probs.asnumpy().reshape(B, H, L, L)
    expect_out = onp.einsum("bhlm,mbhd->lbhd", p, v).reshape(L, B, H * D)
    onp.testing.assert_allclose(out.asnumpy(), expect_out, rtol=1e-4, atol=1e-5)


def test_flash_attention_vs_reference():
    from mxnet_tpu.ops.attention import attention_reference
    from mxnet_tpu.ops.pallas.flash_attention import flash_attention_tpu
    import jax.numpy as jnp
    rng = onp.random.RandomState(0)
    B, H, L, D = 2, 2, 64, 16
    q = rng.randn(B, H, L, D).astype("float32")
    k = rng.randn(B, H, L, D).astype("float32")
    v = rng.randn(B, H, L, D).astype("float32")
    ref = attention_reference(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    # pallas kernel in interpret mode on CPU
    out = flash_attention_tpu(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                              block_q=32, interpret=True)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=1e-4, atol=1e-4)
    # causal
    refc = attention_reference(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                               causal=True)
    outc = flash_attention_tpu(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                               causal=True, block_q=32, interpret=True)
    onp.testing.assert_allclose(onp.asarray(outc), onp.asarray(refc),
                                rtol=1e-4, atol=1e-4)
    # sliding window
    refw = attention_reference(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                               window=8)
    outw = flash_attention_tpu(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                               window=8, block_q=32, interpret=True)
    onp.testing.assert_allclose(onp.asarray(outw), onp.asarray(refw),
                                rtol=1e-4, atol=1e-4)


def test_npx_flash_attention_grad():
    rng = onp.random.RandomState(0)
    B, H, L, D = 1, 2, 16, 8
    q = np.array(rng.randn(B, H, L, D).astype("float32"))
    k = np.array(rng.randn(B, H, L, D).astype("float32"))
    v = np.array(rng.randn(B, H, L, D).astype("float32"))
    for a in (q, k, v):
        a.attach_grad()
    with autograd.record():
        out = npx.flash_attention(q, k, v, causal=True)
        loss = (out ** 2).sum()
    loss.backward()
    for a in (q, k, v):
        g = a.grad.asnumpy()
        assert onp.isfinite(g).all() and onp.abs(g).sum() > 0


def test_flash_attention_pallas_vjp_no_fallback(monkeypatch):
    """Differentiates through the Pallas custom VJP (interpret mode on CPU)
    and FAILS if the dispatcher silently fell back to the XLA path — the
    regression that shipped in round 2."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops import attention
    monkeypatch.setenv("MXNET_FLASH_ATTENTION", "interpret")
    rng = onp.random.RandomState(0)
    B, H, L, D = 1, 2, 64, 16
    q, k, v = (jnp.asarray(rng.randn(B, H, L, D).astype("float32"))
               for _ in range(3))

    def loss_fa(q, k, v):
        return (attention.flash_attention(q, k, v, causal=True) ** 2).sum()

    attention.last_path = None
    g1 = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
    assert attention.last_path == "pallas-interpret", (
        f"expected the Pallas kernel path, got {attention.last_path!r}")

    def loss_ref(q, k, v):
        return (attention.attention_reference(q, k, v, causal=True) ** 2).sum()

    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=1e-3, atol=1e-3)


def test_flash_attention_kv_length_padding():
    """Padding masks ride the kernel as a per-row k-limit (VERDICT r3
    weak #4): numerics + grads must match the masked XLA reference."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.attention import attention_reference
    from mxnet_tpu.ops.pallas.flash_attention import flash_attention_tpu
    rng = onp.random.RandomState(1)
    B, H, L, D = 2, 2, 64, 16
    q, k, v = (jnp.asarray(rng.randn(B, H, L, D).astype("float32"))
               for _ in range(3))
    kv = jnp.asarray([23, 64], jnp.int32)
    out = flash_attention_tpu(q, k, v, kv_length=kv, block_q=32,
                              interpret=True)
    ref = attention_reference(q, k, v, kv_length=kv)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=1e-4, atol=1e-4)
    # combined with causal + grads; padded-beyond rows must stay finite
    g1 = jax.grad(lambda *a: (flash_attention_tpu(
        *a, causal=True, kv_length=kv, block_q=32,
        interpret=True) ** 2).sum(), (0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: (attention_reference(
        *a, causal=True, kv_length=kv) ** 2).sum(), (0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert onp.isfinite(onp.asarray(a)).all()
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=1e-3, atol=1e-3)


def test_flash_attention_in_kernel_dropout():
    """In-kernel hash dropout (VERDICT r3 weak #1): fwd and grads match an
    XLA oracle using the same hash mask; masks differ across seeds."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas.flash_attention import (flash_attention_tpu,
                                                      hash_keep_bits)
    rng = onp.random.RandomState(2)
    B, H, L, D = 2, 2, 64, 16
    q, k, v = (jnp.asarray(rng.randn(B, H, L, D).astype("float32"))
               for _ in range(3))
    rate = 0.25
    seed = jnp.asarray([77], jnp.uint32)

    def oracle(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q / onp.sqrt(D), k)
        p = jax.nn.softmax(s, -1)
        gi = jnp.broadcast_to(jnp.arange(L)[:, None], (L, L))
        gj = jnp.broadcast_to(jnp.arange(L)[None, :], (L, L))
        bits = jax.vmap(lambda b: hash_keep_bits(seed[0], b, gi, gj))(
            jnp.arange(B * H))
        thr = jnp.uint32(int(round(rate * 2 ** 32)))
        keep = (bits >= thr).astype(jnp.float32).reshape(B, H, L, L)
        return jnp.einsum("bhqk,bhkd->bhqd", p * keep / (1 - rate), v)

    out = flash_attention_tpu(q, k, v, dropout=rate, seed=seed, block_q=32,
                              interpret=True)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(oracle(q, k, v)),
                                rtol=1e-4, atol=1e-4)
    g1 = jax.grad(lambda *a: (flash_attention_tpu(
        *a, dropout=rate, seed=seed, block_q=32,
        interpret=True) ** 2).sum(), (0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: (oracle(*a) ** 2).sum(), (0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=1e-3, atol=1e-3)
    # a different seed must change the mask; seed=None w/ dropout=0 is exact
    out2 = flash_attention_tpu(q, k, v, dropout=rate,
                               seed=jnp.asarray([78], jnp.uint32),
                               block_q=32, interpret=True)
    assert float(jnp.max(jnp.abs(out2 - out))) > 1e-3


def test_bert_mha_flash_dropout_and_valid_length(monkeypatch):
    """MultiHeadAttention keeps the flash path under training dropout and
    under a (B,) valid-length mask (the realistic pretraining config)."""
    import mxnet_tpu as mx
    from mxnet_tpu.models.bert import MultiHeadAttention
    from mxnet_tpu.ops import attention
    monkeypatch.setenv("MXNET_FLASH_ATTENTION", "interpret")
    mx.random.seed(3)
    mha = MultiHeadAttention(units=32, num_heads=4, dropout=0.3)
    mha.initialize()
    x = np.array(onp.random.RandomState(4).randn(2, 16, 32).astype("float32"))
    vl = np.array(onp.asarray([9, 16], "int32"))
    with autograd.record(train_mode=True):
        out = mha(x, vl)
        loss = (out ** 2).sum()
    assert attention.last_path == "pallas-interpret", attention.last_path
    loss.backward()
    g = mha.qkv.weight.grad().asnumpy()
    assert onp.isfinite(g).all() and onp.abs(g).sum() > 0
    # two training calls draw different masks (keys advance)
    with autograd.train_mode():
        o1 = mha(x, vl).asnumpy()
        o2 = mha(x, vl).asnumpy()
    assert onp.abs(o1 - o2).max() > 1e-6
    # inference: dropout off, deterministic
    o3 = mha(x, vl).asnumpy()
    o4 = mha(x, vl).asnumpy()
    onp.testing.assert_allclose(o3, o4)


def test_ctc_loss_simple():
    # single perfect-prediction path
    T, B, V = 4, 1, 3
    logits = onp.full((T, B, V), -10.0, "float32")
    # labels [1,2]; alignment 1,1,2,2 (no blanks needed)
    logits[0, 0, 1] = 10
    logits[1, 0, 1] = 10
    logits[2, 0, 2] = 10
    logits[3, 0, 2] = 10
    label = np.array([[1, 2]], dtype="float32")
    loss = npx.ctc_loss(np.array(logits), label).asnumpy()
    assert loss[0] < 1.0  # high-probability path → small loss


def test_ctc_loss_gradient_finite():
    """Regression: the alpha-recursion's where-masked logsumexp used to
    produce inf in the untaken skip branch, whose VJP (inf * 0 = NaN)
    poisoned every gradient — CTC training NaN'd on step one."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.nn import ctc_loss

    rng = onp.random.RandomState(0)
    logits = jnp.asarray(rng.randn(12, 4, 11).astype("float32"))
    label = jnp.asarray(rng.randint(1, 11, size=(4, 4)).astype("float32"))
    val = ctc_loss(logits, label)
    assert bool(jnp.isfinite(val).all())
    g = jax.grad(lambda d: ctc_loss(d, label).sum())(logits)
    assert bool(jnp.isfinite(g).all()), "CTC gradient has NaN/inf"
    assert float(jnp.abs(g).max()) > 0
