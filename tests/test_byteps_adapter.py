"""BytePS kvstore adapter (reference python/mxnet/kvstore/byteps.py):
exercised against a faithful fake bps module — broadcast zeroes non-root
then sum-pushpulls, pushpull sums in place, push/pull raise, capabilities
all False."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np as mxnp
from mxnet_tpu.kvstore import create as kv_create
from mxnet_tpu.kvstore.byteps import KVStoreBytePS


class _FakeBps:
    """Single-process byteps.mxnet stand-in: push_pull over `size` ranks
    multiplies by the rank count (what a sum-allreduce of identical
    contributions produces); declared tensors and calls are recorded."""

    def __init__(self, size=1, rank=0):
        self._size = size
        self._rank = rank
        self.declared = []
        self.calls = []

    def init(self):
        self.calls.append(("init",))

    def rank(self):
        return self._rank

    def size(self):
        return self._size

    def byteps_declare_tensor(self, name):
        self.declared.append(name)

    def byteps_push_pull(self, value, version=0, priority=0, name=None,
                         is_average=False):
        self.calls.append(("push_pull", name, priority, is_average))
        value *= self._size  # in place, like the real core


def test_factory_without_byteps_raises_cleanly():
    try:
        import byteps  # noqa: F401
        pytest.skip("byteps installed")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="tpu_ici"):
        kv_create("byteps")


def test_adapter_delegates_to_bps():
    bps = _FakeBps(size=2, rank=0)
    kv = KVStoreBytePS(bps=bps)
    assert kv.type == "byteps"
    assert kv.rank == 0 and kv.num_workers == 2
    assert ("init",) in bps.calls
    assert not KVStoreBytePS.is_capable("optimizer")

    # broadcast from root rank 0: out receives the summed (=root) value
    v = mxnp.array([1.0, 2.0])
    out = mxnp.zeros(2)
    kv.broadcast("3", v, out=out)
    assert "3" in bps.declared
    assert ("push_pull", "3", 0, False) in bps.calls
    # fake sums rank-0 value over 2 ranks (other rank zeroed in real run);
    # what matters here: value itself was NOT mutated (copy path)
    onp.testing.assert_allclose(v.asnumpy(), [1.0, 2.0])

    # non-root rank zeroes its contribution before the sum
    bps2 = _FakeBps(size=2, rank=1)
    kv2 = KVStoreBytePS(bps=bps2)
    v2 = mxnp.array([5.0, 5.0])
    out2 = mxnp.zeros(2)
    kv2.broadcast("4", v2, out=out2)
    onp.testing.assert_allclose(out2.asnumpy(), [0.0, 0.0])

    # pushpull sums across ranks
    g = mxnp.array([0.5, 0.5])
    tgt = mxnp.zeros(2)
    kv.pushpull("3", g, out=tgt)
    onp.testing.assert_allclose(tgt.asnumpy(), [1.0, 1.0])
    # in-place form: out aliases value
    g2 = mxnp.array([0.25, 0.75])
    kv.pushpull("5", g2, out=g2)
    onp.testing.assert_allclose(g2.asnumpy(), [0.5, 1.5])
    # out=None means in place on value (reference semantics)
    g3 = mxnp.array([1.0, 3.0])
    kv.pushpull("6", g3)
    onp.testing.assert_allclose(g3.asnumpy(), [2.0, 6.0])


def test_push_pull_raise_like_reference():
    kv = KVStoreBytePS(bps=_FakeBps())
    with pytest.raises(NotImplementedError, match="pushpull"):
        kv.push("0", mxnp.ones(2))
    with pytest.raises(NotImplementedError, match="pushpull"):
        kv.pull("0", out=mxnp.ones(2))
    with pytest.raises(NotImplementedError):
        kv.set_optimizer(object())
    # LIST keys batch by looping (gluon.Trainer issues them)
    outs = [mxnp.zeros(2), mxnp.zeros(2)]
    kv.pushpull(["a", "b"], [mxnp.ones(2), mxnp.ones(2) * 2], out=outs)
    onp.testing.assert_allclose(outs[0].asnumpy(), [1.0, 1.0])
    onp.testing.assert_allclose(outs[1].asnumpy(), [2.0, 2.0])


def test_trainer_runs_on_byteps_adapter():
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn
    mx.random.seed(0)
    net = nn.Dense(2, in_units=3)
    net.initialize(mx.init.Xavier())
    kv = KVStoreBytePS(bps=_FakeBps(size=1))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=kv,
                            update_on_kvstore=False)
    x = mxnp.random.uniform(size=(4, 3))
    before = net.weight.data().asnumpy().copy()
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    trainer.step(4)
    after = net.weight.data().asnumpy()
    assert not onp.allclose(before, after)
