"""Horovod kvstore adapter (reference python/mxnet/kvstore/horovod.py):
exercised against a faithful fake hvd module — broadcast roots rank 0,
pushpull is allreduce, push/pull raise like the reference."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np as mxnp
from mxnet_tpu.kvstore import create as kv_create
from mxnet_tpu.kvstore.horovod import KVStoreHorovod


class _FakeHvd:
    """Single-process hvd standing in for horovod.mxnet: allreduce over
    one rank is identity; calls are recorded for assertions."""

    def __init__(self, size=1, rank=0):
        self._size = size
        self._rank = rank
        self.calls = []

    def init(self):
        self.calls.append(("init",))

    def rank(self):
        return self._rank

    def size(self):
        return self._size

    def broadcast(self, value, root_rank=0, name=None, priority=0):
        self.calls.append(("broadcast", name, root_rank))
        return value

    def allreduce(self, value, average=False, name=None, priority=0):
        self.calls.append(("allreduce", name, average))
        return value * self._size  # what a real sum-allreduce produces


def test_factory_without_horovod_raises_cleanly():
    try:
        import horovod  # noqa: F401
        pytest.skip("horovod installed")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="tpu_ici"):
        kv_create("horovod")


def test_adapter_delegates_to_hvd():
    hvd = _FakeHvd(size=2, rank=1)
    kv = KVStoreHorovod(hvd=hvd)
    assert kv.type == "horovod"
    assert kv.rank == 1 and kv.num_workers == 2
    assert ("init",) in hvd.calls

    v = mxnp.array([1.0, 2.0])
    out = mxnp.zeros(2)
    kv.broadcast("3", v, out=out)
    assert ("broadcast", "3", 0) in hvd.calls
    onp.testing.assert_allclose(out.asnumpy(), [1.0, 2.0])

    g = mxnp.array([0.5, 0.5])
    tgt = mxnp.zeros(2)
    kv.pushpull("3", g, out=tgt)
    assert ("allreduce", "3", False) in hvd.calls
    onp.testing.assert_allclose(tgt.asnumpy(), [1.0, 1.0])  # sum over 2


def test_push_pull_raise_like_reference():
    kv = KVStoreHorovod(hvd=_FakeHvd())
    with pytest.raises(NotImplementedError, match="allreduce"):
        kv.push("0", mxnp.ones(2))
    with pytest.raises(NotImplementedError, match="allreduce"):
        kv.pull("0", out=mxnp.ones(2))
    with pytest.raises(NotImplementedError):
        kv.set_optimizer(object())


def test_trainer_runs_on_horovod_adapter():
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn
    mx.random.seed(0)
    net = nn.Dense(2, in_units=3)
    net.initialize(mx.init.Xavier())
    kv = KVStoreHorovod(hvd=_FakeHvd(size=1))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=kv,
                            update_on_kvstore=False)
    x = mxnp.random.uniform(size=(4, 3))
    before = net.weight.data().asnumpy().copy()
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    trainer.step(4)
    after = net.weight.data().asnumpy()
    assert not onp.allclose(before, after)
