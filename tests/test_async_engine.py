"""Async decode engine: step pipelining parity + behavior (`llm`
marker, CPU tier-1).

The async engine reorders WHEN host work happens (launch/retire halves,
device-resident token chaining, deferred reads) but must never change
WHAT is computed.  The acceptance matrix:

- greedy bit-parity with the synchronous engine across the serving
  feature matrix: plain decode, speculative k∈{1,2}, prefix-cache CoW,
  chunked prefill, preemption under page pressure, int8 KV;
- the static launch census is identical to sync — pipelining reorders
  dispatch, it must not add programs;
- reused staging buffers never force a recompile mid-stream;
- an injected ``engine.retire`` fault fails ONLY the poisoned flight's
  lanes (typed), flushes the pipeline, and the engine keeps serving;
- deadlines judged at launch/retire still terminate mid-decode under a
  deep dispatch queue;
- drain with launches in flight completes every stream bit-exactly and
  returns occupancy to zero (pinned in-flight pages are conserved).
"""
from __future__ import annotations

import time

import pytest

import jax

from mxnet_tpu import faults, serving
from mxnet_tpu.models import decoder

pytestmark = pytest.mark.llm

VOCAB = 128

PROMPTS = [[1, 2, 3], [7, 5], [2, 9, 4, 1], [3], [11, 3, 7]]


@pytest.fixture(scope="module")
def lm():
    return decoder.decoder_tiny_lm(seed=0, vocab_size=VOCAB)


def make_engine(lm, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("max_ctx", 64)
    return serving.DecodeEngine(lm, name="llm", **kw)


def run_workload(lm, prompts, max_new=8, **kw):
    eng = make_engine(lm, **kw)
    try:
        futs = [eng.submit(list(p), max_new_tokens=max_new)
                for p in prompts]
        out = [f.result(timeout=300)["tokens"] for f in futs]
    finally:
        assert eng.stop()
    assert eng.alloc.num_used == 0
    eng.alloc.check_leaks()
    return out


# ---------------------------------------------------------------------------
# bit-parity matrix
# ---------------------------------------------------------------------------
MATRIX = {
    "plain": {},
    "spec_k1": {"speculate": True, "spec_k": 1},
    "spec_k2": {"speculate": True, "spec_k": 2},
    "prefix_cow": {"prefix_cache": True},
    "chunked_prefill": {"prefill_chunk": 4},
    "preemption": {"slots": 3, "page_size": 4, "max_ctx": 32,
                   "total_pages": 9},
    "int8_kv": {"kv_dtype": "int8"},
}


@pytest.mark.parametrize("case", sorted(MATRIX), ids=sorted(MATRIX))
def test_async_sync_greedy_bit_parity(lm, case):
    """Token streams are IDENTICAL with pipelining on and off: the
    async engine is a scheduling change, not a numerics change."""
    kw = dict(MATRIX[case])
    prompts = PROMPTS
    if case == "chunked_prefill":
        # prompts longer than the chunk so prefill spans many steps
        # while decode lanes have launches in flight
        prompts = [list(range(1, 20)), list(range(2, 12)), [5, 6, 7]]
    if case == "prefix_cow":
        shared = list(range(1, 18))  # 2 full pages + a partial
        prompts = [shared + [20, 21], shared + [30, 31], shared + [40]]
    a = run_workload(lm, prompts, async_decode=True, **kw)
    s = run_workload(lm, prompts, async_decode=False, **kw)
    assert a == s


def test_async_session_continuation_matches_one_shot(lm):
    """Session park/resume while flights are in the pipe: continuation
    still equals the one-shot stream and parked pages survive pinning."""
    eng = make_engine(lm, async_decode=True)
    try:
        r1 = eng.submit([1, 2, 3], max_new_tokens=4,
                        session="s").result(timeout=120)
        r2 = eng.submit([7, 8], max_new_tokens=4, session="s",
                        resume=True).result(timeout=120)
        oneshot = eng.submit([1, 2, 3] + r1["tokens"] + [7, 8],
                             max_new_tokens=4).result(timeout=120)
        assert r2["tokens"] == oneshot["tokens"]
        assert eng.alloc.num_used > 0  # parked session holds its pages
    finally:
        assert eng.stop()
    assert eng.alloc.num_used == 0
    eng.alloc.check_leaks()


@pytest.mark.multichip
def test_async_tp_bit_parity(lm):
    """Tensor-parallel serving (8 fake devices) under pipelining."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices "
                    "(XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    from mxnet_tpu.parallel.shardcfg import ShardingConfig
    cfg = ShardingConfig.for_transformer(mesh_shape=(4, 2),
                                         axis_names=("dp", "tp"))
    a = run_workload(lm, PROMPTS, async_decode=True, sharding=cfg)
    s = run_workload(lm, PROMPTS, async_decode=False, sharding=cfg)
    assert a == s


# ---------------------------------------------------------------------------
# launch census + staging recompiles
# ---------------------------------------------------------------------------
def test_async_launch_census_identical_to_sync(lm):
    """Pipelining reorders launches; it must not change the static
    decode program census (fused + tower counts) the tier-1 launch
    gates pin down."""
    a = make_engine(lm, async_decode=True)
    s = make_engine(lm, async_decode=False)
    try:
        assert a.decode_fused_mode == s.decode_fused_mode
        assert dict(a.launch_stats) == dict(s.launch_stats)
    finally:
        a.stop(drain=False)
        s.stop(drain=False)


def test_async_staging_buffers_no_recompile(lm):
    """Pinned staging buffers + the chaining combine are compiled once
    at warmup; steady-state steps add ZERO program-cache compiles."""
    eng = make_engine(lm, async_decode=True)
    try:
        eng.warmup()
        before = decoder.fn_cache_stats()["compiles"]
        for rnd in range(2):
            futs = [eng.submit([rnd + 1, i + 2], max_new_tokens=6)
                    for i in range(4)]
            for f in futs:
                assert len(f.result(timeout=120)["tokens"]) == 6
        assert decoder.fn_cache_stats()["compiles"] == before
    finally:
        assert eng.stop()
    eng.alloc.check_leaks()


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------
def test_async_metrics_and_stats_surface(lm):
    eng = make_engine(lm, async_decode=True, dispatch_ahead=2)
    try:
        st = eng.stats()["async"]
        assert st == {"enabled": True, "dispatch_ahead": 2, "inflight": 0}
        futs = [eng.submit(p, max_new_tokens=10) for p in PROMPTS]
        for f in futs:
            f.result(timeout=120)
        snap = eng.metrics.snapshot()["models"]["llm"]
        assert snap["counters"]["deferred_reads_total"] > 0
        assert snap["generate"]["dispatch_depth"]["count"] > 0
        assert snap["generate"]["dispatch_depth"]["max"] >= 1
        assert snap["generate"]["host_gap_us"]["count"] > 0
        assert eng.stats()["async"]["inflight"] == 0  # all retired
    finally:
        assert eng.stop()


def test_sync_engine_reports_host_gap_for_ab(lm):
    """The sync path records the same host-gap metric so the A/B bench
    can quantify what pipelining hides."""
    eng = make_engine(lm, async_decode=False)
    try:
        eng.submit([1, 2, 3], max_new_tokens=8).result(timeout=120)
        snap = eng.metrics.snapshot()["models"]["llm"]
        assert snap["generate"]["host_gap_us"]["count"] > 0
        assert snap["counters"].get("deferred_reads_total", 0) == 0
    finally:
        assert eng.stop()


# ---------------------------------------------------------------------------
# fault injection: engine.retire
# ---------------------------------------------------------------------------
def test_engine_retire_fault_poisons_flight_only(lm):
    """A retire fault fails exactly the poisoned flight's lanes
    (typed ServingError), discards the rest of the pipeline, and the
    engine keeps serving with a clean page pool."""
    eng = make_engine(lm, async_decode=True, prefix_cache=False)
    try:
        with faults.inject("engine.retire", "error", n=1, max_trips=1):
            fut = eng.submit([1, 2, 3], max_new_tokens=10)
            with pytest.raises(serving.ServingError):
                fut.result(timeout=120)
        assert eng.alloc.num_used == 0  # poisoned lanes freed their pages
        res = eng.submit([1, 2, 3], max_new_tokens=4).result(timeout=120)
        ref = run_workload(lm, [[1, 2, 3]], max_new=4, async_decode=False)
        assert res["tokens"] == ref[0]
        snap = eng.metrics.snapshot()["models"]["llm"]
        assert snap["counters"]["errors_total"] >= 1
    finally:
        assert eng.stop()
    assert eng.alloc.num_used == 0
    eng.alloc.check_leaks()


def test_engine_retire_fault_speculative_pipeline(lm):
    """Same contract with the speculative pipeline in flight."""
    eng = make_engine(lm, async_decode=True, speculate=True, spec_k=2,
                      prefix_cache=False)
    try:
        with faults.inject("engine.retire", "error", n=1, max_trips=1):
            fut = eng.submit([2, 9, 4], max_new_tokens=10)
            with pytest.raises(serving.ServingError):
                fut.result(timeout=120)
        res = eng.submit([2, 9, 4], max_new_tokens=5).result(timeout=120)
        ref = run_workload(lm, [[2, 9, 4]], max_new=5, async_decode=False)
        assert res["tokens"] == ref[0]
    finally:
        assert eng.stop()
    assert eng.alloc.num_used == 0
    eng.alloc.check_leaks()


# ---------------------------------------------------------------------------
# deadlines + drain under a deep pipeline
# ---------------------------------------------------------------------------
def test_async_deadline_expires_under_deep_queue(lm):
    """Deadlines are judged against launch/retire time: with a deep
    dispatch queue an expired stream still terminates promptly with
    finish_reason="deadline" instead of riding the pipeline forever."""
    eng = make_engine(lm, async_decode=True, dispatch_ahead=3,
                      max_ctx=128)
    try:
        eng.warmup()  # deadline must land mid-DECODE, not mid-compile
        # pace one short stream, then give a 120-token stream about the
        # SHORT stream's wall time (~1/6 of its own projection) — the
        # box would have to run ~6x faster than the probe for the
        # stream to hit its length budget before the deadline
        t0 = time.perf_counter()
        eng.submit([9, 9], max_new_tokens=20).result(timeout=120)
        pace = time.perf_counter() - t0
        res = eng.submit([1, 2, 3], max_new_tokens=120,
                         deadline_ms=max(10.0, 1e3 * pace)).result(
                             timeout=120)
        assert res["finish_reason"] == "deadline"
        assert len(res["tokens"]) < 120
    finally:
        assert eng.stop()
    assert eng.alloc.num_used == 0
    eng.alloc.check_leaks()


def test_async_drain_mid_pipeline_completes_all(lm):
    """stop(drain=True) issued while launches are in flight: the worker
    drains the pipe, every stream completes bit-exactly, occupancy ends
    at zero (in-flight pins all released)."""
    ref = run_workload(lm, PROMPTS, max_new=12, async_decode=False)
    eng = make_engine(lm, async_decode=True, dispatch_ahead=2)
    futs = [eng.submit(list(p), max_new_tokens=12) for p in PROMPTS]
    time.sleep(0.2)  # let the pipeline fill mid-generation
    assert eng.stop(drain=True)
    assert [f.result(timeout=10)["tokens"] for f in futs] == ref
    assert eng.alloc.num_used == 0
    eng.alloc.check_leaks()


def test_async_migrate_out_parity(lm):
    """Parked sessions ship to the page store with flights retired; a
    survivor resumes the stream bit-exactly (mid-pipeline migration)."""
    from mxnet_tpu.kvstore.pagestore import PageStoreServer
    store = PageStoreServer()
    store.start()
    try:
        a = make_engine(lm, async_decode=True, pagestore=store.address,
                        prefix_cache=False)
        try:
            r1 = a.submit([1, 2, 3], max_new_tokens=4,
                          session="m").result(timeout=120)
            assert a.migrate_out() == 1
            assert a.alloc.num_used == 0  # pinned pages fully released
        finally:
            a.stop(drain=False)
        b = make_engine(lm, async_decode=True, pagestore=store.address)
        try:
            r2 = b.submit([7, 8], max_new_tokens=4, session="m",
                          resume=True).result(timeout=120)
        finally:
            b.stop(drain=False)
        oneshot = run_workload(lm, [[1, 2, 3] + r1["tokens"] + [7, 8]],
                               max_new=4, async_decode=False)
        assert r2["tokens"] == oneshot[0]
    finally:
        store.stop()
