"""Composable mx.sym graph API (parity: reference symbol.py:57 —
var/compose/arithmetic/bind/eval/Group/save/load + legacy ops with
implicit parameter variables) and its round-trips through SymbolBlock."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np as mxnp
from mxnet_tpu import sym_api as sym


def test_var_compose_arithmetic_eval():
    a = sym.var("a")
    b = sym.var("b")
    c = (a + b) * 2 - a / b
    assert sorted(c.list_arguments()) == ["a", "b"]
    av = mxnp.array([1.0, 2.0])
    bv = mxnp.array([4.0, 8.0])
    (out,) = c.eval(a=av, b=bv)
    onp.testing.assert_allclose(
        out.asnumpy(), (onp.array([1, 2.]) + [4, 8.]) * 2 - [.25, .25])


def test_generic_np_ops_symbolically():
    x = sym.var("x")
    y = sym.exp(sym.sin(x)) + sym.sum(x)
    (out,) = y.eval(x=mxnp.array([0.1, 0.2]))
    ref = onp.exp(onp.sin([0.1, 0.2])) + onp.sum([0.1, 0.2])
    onp.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-6)


def test_legacy_fc_auto_creates_weight_vars():
    data = sym.var("data")
    fc = sym.FullyConnected(data, num_hidden=3, name="fc1")
    assert fc.list_arguments() == ["data", "fc1_weight", "fc1_bias"]
    args, outs, _aux = fc.infer_shape(data=(4, 5))
    assert args == [(4, 5), (3, 5), (3,)]
    assert outs == [(4, 3)]


def test_legacy_mlp_bind_forward_backward():
    data = sym.var("data")
    h = sym.Activation(sym.FullyConnected(data, num_hidden=8, name="fc1"),
                       act_type="relu")
    out = sym.FullyConnected(h, num_hidden=3, name="fc2")
    ex = out.simple_bind(data=(4, 6))
    rng = onp.random.RandomState(0)
    for k in ex.arg_dict:
        ex.arg_dict[k] = mxnp.array(
            rng.uniform(-1, 1, ex.arg_dict[k].shape).astype("float32"))
    (o,) = ex.forward()
    assert o.shape == (4, 3)
    # reference forward in numpy
    a = ex.arg_dict
    relu = lambda v: onp.maximum(v, 0)
    ref = relu(a["data"].asnumpy() @ a["fc1_weight"].asnumpy().T
               + a["fc1_bias"].asnumpy()) @ a["fc2_weight"].asnumpy().T \
        + a["fc2_bias"].asnumpy()
    onp.testing.assert_allclose(o.asnumpy(), ref, rtol=1e-5, atol=1e-5)
    grads = ex.backward()
    assert set(ex.grad_dict) == set(ex.arg_dict)
    # numeric check on fc2_bias: d(sum(out))/d(bias) = batch count
    onp.testing.assert_allclose(ex.grad_dict["fc2_bias"].asnumpy(),
                                onp.full(3, 4.0), rtol=1e-5)


def test_convolution_batchnorm_compose_and_shapes():
    data = sym.var("data")
    c = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                        name="c1")
    bn = sym.BatchNorm(c, name="bn1")
    p = sym.Pooling(bn, kernel=(2, 2), stride=(2, 2), pool_type="max")
    f = sym.Flatten(p)
    args, outs, auxs = f.infer_shape(data=(2, 3, 8, 8))
    assert outs == [(2, 8 * 4 * 4)]
    assert f.list_auxiliary_states() == ["bn1_moving_mean",
                                         "bn1_moving_var"]
    names = f.list_arguments()
    assert names[0] == "data" and "c1_weight" in names and \
        "bn1_gamma" in names
    ex = f.simple_bind(data=(2, 3, 8, 8))
    (out,) = ex.forward()
    assert out.shape == (2, 128)


def test_group_and_get_internals():
    x = sym.var("x")
    a = sym.sin(x, name="s")
    b = sym.cos(x, name="c")
    g = sym.Group([a, b])
    outs = g.eval(x=mxnp.array([0.5]))
    onp.testing.assert_allclose(outs[0].asnumpy(), onp.sin([0.5]), rtol=1e-6)
    onp.testing.assert_allclose(outs[1].asnumpy(), onp.cos([0.5]), rtol=1e-6)
    internals = (a + b).get_internals()
    assert any(n.name == "s" for n in internals._inputs)
    s_node = (a + b)["s"]
    (sv,) = s_node.eval(x=mxnp.array([0.5]))
    onp.testing.assert_allclose(sv.asnumpy(), onp.sin([0.5]), rtol=1e-6)


def test_json_roundtrip():
    data = sym.var("data", shape=(2, 4), dtype="float32")
    net = sym.FullyConnected(data, num_hidden=3, name="fc") + 1.0
    text = net.tojson()
    back = sym.fromjson(text)
    assert back.list_arguments() == net.list_arguments()
    rng = onp.random.RandomState(1)
    env = {"data": mxnp.array(rng.randn(2, 4).astype("float32")),
           "fc_weight": mxnp.array(rng.randn(3, 4).astype("float32")),
           "fc_bias": mxnp.zeros(3)}
    (o1,) = net.eval(**env)
    (o2,) = back.eval(**env)
    onp.testing.assert_allclose(o1.asnumpy(), o2.asnumpy(), rtol=1e-6)


def test_export_artifact_and_symbolblock_imports(tmp_path):
    from mxnet_tpu.gluon import SymbolBlock
    data = sym.var("data", shape=(2, 4), dtype="float32")
    net = sym.FullyConnected(data, num_hidden=3, name="fc")
    rng = onp.random.RandomState(2)
    w = rng.randn(3, 4).astype("float32")
    b = rng.randn(3).astype("float32")
    art, pvals = net.export_artifact(
        {"fc_weight": mxnp.array(w), "fc_bias": mxnp.array(b)})
    sym_file = str(tmp_path / "net-symbol.json")
    art.save(sym_file)
    param_file = str(tmp_path / "net-0000.params.npz")
    onp.savez(param_file, **{k: onp.asarray(v) for k, v in pvals.items()})
    blk = SymbolBlock.imports(sym_file, ["data"], param_file)
    x = rng.randn(2, 4).astype("float32")
    out = blk(mxnp.array(x))
    onp.testing.assert_allclose(out.asnumpy(), x @ w.T + b,
                                rtol=1e-5, atol=1e-5)


def test_symbolblock_imports_dag_json(tmp_path):
    from mxnet_tpu.gluon import SymbolBlock
    data = sym.var("data")
    net = sym.Activation(sym.FullyConnected(data, num_hidden=4, name="fc"),
                         act_type="tanh")
    f = str(tmp_path / "dag-symbol.json")
    net.save(f)
    rng = onp.random.RandomState(3)
    w = rng.randn(4, 5).astype("float32")
    b = rng.randn(4).astype("float32")
    pf = str(tmp_path / "dag-0000.params.npz")
    onp.savez(pf, fc_weight=w, fc_bias=b)
    blk = SymbolBlock.imports(f, ["data"], pf)
    x = rng.randn(2, 5).astype("float32")
    out = blk(mxnp.array(x))
    onp.testing.assert_allclose(out.asnumpy(), onp.tanh(x @ w.T + b),
                                rtol=1e-5, atol=1e-5)


def test_mx_namespace_exposes_sym():
    assert mx.sym.var is sym.var
    assert callable(mx.sym.FullyConnected)


def test_unbound_variable_raises():
    x = sym.var("x")
    y = sym.var("y")
    with pytest.raises(ValueError, match="unbound variable"):
        (x + y).eval(x=mxnp.ones(2))


def test_executor_rebind_kwargs_and_is_train_dropout():
    x = sym.var("x")
    d = sym.Dropout(x, p=0.5)
    ex = d.bind(args={"x": mxnp.ones((100,))})
    (o_eval,) = ex.forward(is_train=False)
    onp.testing.assert_allclose(o_eval.asnumpy(), onp.ones(100))
    (o_train,) = ex.forward(is_train=True)
    assert (onp.asarray(o_train.asnumpy()) == 0).any()


def test_softmax_output_backward_softmax_minus_label():
    """ADVICE r2: legacy SoftmaxOutput must emit (softmax - label) wrt data
    under ex.backward() with default ones out_grads (reference
    softmax_output.cc), not the zero gradient of d/dx sum(softmax)."""
    data = sym.var("data")
    label = sym.var("label")
    out = sym.SoftmaxOutput(data=data, label=label)
    rng = onp.random.RandomState(0)
    x = rng.randn(4, 5).astype("float32")
    y = onp.array([1, 0, 3, 2])
    ex = out.bind(args={"data": mxnp.array(x), "label": mxnp.array(y)})
    ex.forward(is_train=True)
    ex.backward()
    g = ex.grad_dict["data"].asnumpy()
    p = onp.exp(x) / onp.exp(x).sum(-1, keepdims=True)
    onp.testing.assert_allclose(g, p - onp.eye(5)[y], rtol=1e-5, atol=1e-6)
    # grad_scale honored
    out2 = sym.SoftmaxOutput(data=data, label=label, grad_scale=0.5)
    ex2 = out2.bind(args={"data": mxnp.array(x), "label": mxnp.array(y)})
    ex2.forward(is_train=True)
    ex2.backward()
    onp.testing.assert_allclose(ex2.grad_dict["data"].asnumpy(),
                                0.5 * (p - onp.eye(5)[y]),
                                rtol=1e-5, atol=1e-6)


def test_generic_factory_scalar_positional_order():
    """ADVICE r2: scalar positionals that precede Symbol args must keep
    their call position (sym.subtract(2.0, x) != x - 2)."""
    x = sym.var("x")
    v = mxnp.array([1.0, 2.0, 4.0])
    r = sym.subtract(2.0, x).eval(x=v)[0].asnumpy()
    onp.testing.assert_allclose(r, 2.0 - v.asnumpy())
    r = sym.true_divide(1, x).eval(x=v)[0].asnumpy()
    onp.testing.assert_allclose(r, 1.0 / v.asnumpy())
    c = sym.var("c")
    r = sym.where(c, 0.0, x).eval(c=mxnp.array([1, 0, 1]), x=v)[0].asnumpy()
    onp.testing.assert_allclose(r, onp.where([1, 0, 1], 0.0, v.asnumpy()))
    # trailing non-symbol positionals still ride as attrs (shape here)
    assert sym.reshape(x, (3, 1)).eval(x=v)[0].shape == (3, 1)


def test_softmax_output_use_ignore_and_valid_normalization():
    data = sym.var("data")
    label = sym.var("label")
    out = sym.SoftmaxOutput(data=data, label=label, use_ignore=True,
                            ignore_label=-1, normalization="valid")
    rng = onp.random.RandomState(1)
    x = rng.randn(4, 5).astype("float32")
    y = onp.array([1, -1, 3, -1])
    ex = out.bind(args={"data": mxnp.array(x), "label": mxnp.array(y)})
    ex.forward(is_train=True)
    ex.backward()
    g = ex.grad_dict["data"].asnumpy()
    p = onp.exp(x) / onp.exp(x).sum(-1, keepdims=True)
    # ignored rows get exactly zero grad; valid rows divided by #valid (=2)
    assert onp.abs(g[1]).max() == 0 and onp.abs(g[3]).max() == 0
    expect = (p[0] - onp.eye(5)[1]) / 2.0
    onp.testing.assert_allclose(g[0], expect, rtol=1e-5, atol=1e-6)
