"""Distributed/SPMD tests on the 8-device virtual CPU mesh (reference
analog: tests/nightly/dist_*_kvstore.py run multi-process-on-one-host;
here: multi-device mesh in one process, SURVEY §4 implication (3))."""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import np, gluon, autograd
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import (DataParallelTrainer, Mesh, P, make_mesh,
                                functionalize)
from mxnet_tpu.parallel.ring_attention import ring_attention
from mxnet_tpu.ops.attention import attention_reference

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8-device mesh")


def test_make_mesh():
    mesh = make_mesh(axis_names=("dp",))
    assert mesh.shape["dp"] == 8
    mesh2 = make_mesh((4, 2), ("dp", "tp"))
    assert mesh2.shape["dp"] == 4 and mesh2.shape["tp"] == 2


def test_data_parallel_trainer_matches_single_device():
    """DP over 8 devices must produce the same updates as one device."""
    def run(mesh):
        mx.random.seed(5)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
        net.initialize()
        x = np.random.uniform(size=(16, 8))
        y = np.random.randint(0, 4, size=(16,))
        net(x[:1])
        loss_obj = gluon.loss.SoftmaxCrossEntropyLoss()
        tr = DataParallelTrainer(net, lambda o, l: loss_obj(o, l), "sgd",
                                 {"learning_rate": 0.1}, mesh=mesh)
        state = tr.init_state()
        tr.build_step(donate=False)
        key = jax.random.key(0)
        losses = []
        for _ in range(3):
            state, loss = tr.step(state, x, y, key, 0.1)
            losses.append(float(loss))
        return losses, {k: onp.asarray(v) for k, v in state["params"].items()}

    l8, p8 = run(make_mesh((8,), ("dp",)))
    l1, p1 = run(Mesh(onp.array(jax.devices()[:1]), ("dp",)))
    onp.testing.assert_allclose(l8, l1, rtol=1e-5)
    for k in p8:
        onp.testing.assert_allclose(p8[k], p1[k], rtol=1e-4, atol=1e-5)


def test_tensor_parallel_matches_replicated():
    mx.random.seed(1)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(8))
    net.initialize()
    x = np.random.uniform(size=(8, 4))
    y = np.random.randint(0, 8, size=(8,))
    net(x[:1])
    loss_obj = gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = make_mesh((4, 2), ("dp", "tp"))

    def pspec(name, shape):
        if name.endswith("weight") and len(shape) == 2 and shape[0] % 2 == 0:
            return P("tp", None)
        return P()

    results = []
    for spec_fn in (pspec, None):
        mx.random.seed(1)
        tr = DataParallelTrainer(net, lambda o, l: loss_obj(o, l), "sgd",
                                 {"learning_rate": 0.1}, mesh=mesh,
                                 param_pspec=spec_fn, data_axis="dp")
        state = tr.init_state()
        tr.build_step(donate=False)
        losses = []
        for _ in range(3):
            state, loss = tr.step(state, x, y, jax.random.key(0), 0.1)
            losses.append(float(loss))
        results.append(losses)
    onp.testing.assert_allclose(results[0], results[1], rtol=1e-4)


@pytest.mark.parametrize("causal,window", [
    pytest.param(False, None, marks=pytest.mark.slow),
    pytest.param(True, None, marks=pytest.mark.slow),
    pytest.param(False, 16, marks=pytest.mark.slow)])
def test_ring_attention_matches_reference(causal, window):
    """Ring attention over an 8-way sequence shard == single-device
    attention."""
    rng = onp.random.RandomState(0)
    B, H, L, D = 2, 2, 64, 8
    q = jnp.asarray(rng.randn(B, H, L, D).astype("float32"))
    k = jnp.asarray(rng.randn(B, H, L, D).astype("float32"))
    v = jnp.asarray(rng.randn(B, H, L, D).astype("float32"))
    mesh = make_mesh((8,), ("sp",))
    out = ring_attention(q, k, v, mesh, seq_axis="sp", causal=causal,
                         window=window)
    ref = attention_reference(q, k, v, causal=causal, window=window)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=2e-4, atol=2e-4)


def test_ring_attention_jit_grad():
    """Ring attention is differentiable and jittable over the mesh."""
    rng = onp.random.RandomState(0)
    B, H, L, D = 1, 2, 32, 8
    q = jnp.asarray(rng.randn(B, H, L, D).astype("float32"))
    k = jnp.asarray(rng.randn(B, H, L, D).astype("float32"))
    v = jnp.asarray(rng.randn(B, H, L, D).astype("float32"))
    mesh = make_mesh((8,), ("sp",))

    def loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    for gi in g:
        arr = onp.asarray(gi)
        assert onp.isfinite(arr).all() and onp.abs(arr).sum() > 0


def test_kvstore_multi_value_reduce():
    from mxnet_tpu import kvstore
    kv = kvstore.create("device")
    vals = [np.ones((4,)) * i for i in range(4)]
    out = np.zeros((4,))
    kv.pushpull("w", vals, out=out)
    onp.testing.assert_array_equal(out.asnumpy(), 6 * onp.ones(4))


def test_kvstore_updater_path():
    from mxnet_tpu import kvstore, optimizer
    kv = kvstore.create("local")
    kv.set_optimizer(optimizer.SGD(learning_rate=1.0))
    w = np.ones((3,))
    kv.init(0, w)
    g = np.full((3,), 0.1)
    kv.push(0, g)
    out = np.zeros((3,))
    kv.pull(0, out=out)
    onp.testing.assert_allclose(out.asnumpy(), 0.9 * onp.ones(3), rtol=1e-6)


def test_functionalize_roundtrip():
    net = nn.Dense(4, in_units=3)
    net.initialize()
    fn, params = functionalize(net)
    pvals = {k: p._data._data for k, p in params.items()}
    x = jnp.ones((2, 3))
    out, aux = fn(pvals, x)
    assert out.shape == (2, 4)
    assert aux == {}
    # jittable
    out2, _ = jax.jit(fn)(pvals, x)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(out2),
                                rtol=1e-6)


def test_split_and_load():
    from mxnet_tpu.gluon.utils import split_and_load, split_data
    x = np.arange(16).reshape(8, 2)
    parts = split_data(x, 4)
    assert len(parts) == 4 and parts[0].shape == (2, 2)
    ctxs = [mx.cpu(0), mx.cpu(1)]
    loaded = split_and_load(x, ctxs)
    assert len(loaded) == 2
