/* mxtpu C training API — public header for non-Python embedders.
 *
 * Parity: the moral core of the reference include/mxnet/c_api.h (NDArray
 * lifecycle, imperative invoke, autograd, CachedOp, KVStore, optimizer)
 * plus a packed-function-style generic entry.  Link libmxtpu_capi.so
 * (`make -C src capi`); every function returns 0 on success, -1 on error
 * (message via MXTGetLastError, thread-local).  Handles must be released
 * with the matching *Free.  The inference-only surface lives in
 * libmxtpu_predict.so (MXTPred*).
 */
#ifndef MXTPU_C_API_H_
#define MXTPU_C_API_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* MXTHandle;

const char* MXTGetLastError(void);
int MXTVersion(int* out);

/* NDArray lifecycle */
int MXTNDArrayCreate(const int64_t* shape, int ndim, const char* dtype,
                     MXTHandle* out);
int MXTNDArrayFromBytes(const int64_t* shape, int ndim, const char* dtype,
                        const void* data, size_t nbytes, MXTHandle* out);
int MXTNDArraySyncCopyToCPU(MXTHandle handle, void* data, size_t nbytes);
int MXTNDArrayGetShape(MXTHandle handle, int* ndim, int64_t* shape, int cap);
int MXTNDArrayGetDType(MXTHandle handle, char* buf, int buflen);
int MXTNDArrayFree(MXTHandle handle);
int MXTNDArrayWaitAll(void);

/* imperative op invoke: op resolved in mx.npx then mx.np; kwargs as JSON
 * (lists become tuples python-side).  outs/nout: caller passes capacity,
 * receives count. */
int MXTImperativeInvoke(const char* op, MXTHandle* ins, int nin,
                        const char* kwargs_json, MXTHandle* outs, int* nout);
int MXTListOps(char** csv_out); /* free with MXTStringFree */
void MXTStringFree(char* s);

/* autograd */
int MXTAutogradSetRecording(int flag, int* prev);
int MXTAutogradSetTraining(int flag, int* prev);
int MXTAutogradMarkVariables(int n, MXTHandle* handles);
int MXTAutogradBackward(int n, MXTHandle* heads, int retain_graph);
int MXTNDArrayGetGrad(MXTHandle handle, MXTHandle* out);

/* optimizer (updater with per-index state, reference updater semantics) */
int MXTOptimizerCreate(const char* opt_type, const char* kwargs_json,
                       MXTHandle* out);
int MXTOptimizerUpdate(MXTHandle opt, int index, MXTHandle weight,
                       MXTHandle grad);
int MXTOptimizerFree(MXTHandle opt);

/* CachedOp: bind an mx.sym JSON graph, invoke positionally over
 * list_arguments() order */
int MXTCachedOpCreate(const char* symbol_json, MXTHandle* out);
int MXTCachedOpInvoke(MXTHandle handle, MXTHandle* ins, int nin,
                      MXTHandle* outs, int* nout);
int MXTCachedOpFree(MXTHandle handle);

/* kvstore */
int MXTKVStoreCreate(const char* kind, MXTHandle* out);
int MXTKVStoreInit(MXTHandle kv, int n, const int* keys, MXTHandle* vals);
int MXTKVStorePush(MXTHandle kv, int n, const int* keys, MXTHandle* vals,
                   int priority);
int MXTKVStorePull(MXTHandle kv, int n, const int* keys, MXTHandle* outs,
                   int priority);
int MXTKVStoreFree(MXTHandle kv);

/* misc */
int MXTRandomSeed(int seed);

/* packed-function analog: call any public mxnet_tpu callable by dotted
 * path with JSON args; result returned as JSON (arrays cannot cross this
 * boundary — use the handle-based entries for tensors). */
int MXTGenericInvoke(const char* path, const char* json_in, char** json_out);

#ifdef __cplusplus
}
#endif

#endif /* MXTPU_C_API_H_ */
