#!/usr/bin/env python
"""INT8 post-training quantization driver (parity: reference
example/quantization/imagenet_gen_qsym_onednn.py + imagenet_inference.py
collapsed into one Gluon-era script).

Calibrates a model-zoo network on sample data, quantizes Dense/Conv to
int8, and reports accuracy agreement + throughput vs fp32.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import np as mxnp
from mxnet_tpu.contrib import quantization as q
from mxnet_tpu.gluon.model_zoo.vision import get_model


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet18_v1")
    ap.add_argument("--calib-mode", default="entropy",
                    choices=["naive", "entropy"])
    ap.add_argument("--num-calib-batches", type=int, default=4)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--image-shape", type=int, default=32)
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    mx.random.seed(0)
    net = get_model(args.model, classes=10)
    net.initialize(mx.init.Xavier())
    rng = onp.random.RandomState(0)

    def batch():
        return mxnp.array(rng.rand(args.batch_size, 3, args.image_shape,
                                   args.image_shape).astype(onp.float32))

    x = batch()
    ref = net(x).asnumpy()
    t0 = time.time()
    for _ in range(args.iters):
        net(x).wait_to_read()
    fp32_ips = args.iters * args.batch_size / (time.time() - t0)

    calib = [batch() for _ in range(args.num_calib_batches)]
    q.quantize_net(net, calib_data=calib, calib_mode=args.calib_mode)
    out = net(x).asnumpy()
    agree = (out.argmax(1) == ref.argmax(1)).mean()

    net(x).wait_to_read()
    t0 = time.time()
    for _ in range(args.iters):
        net(x).wait_to_read()
    int8_ips = args.iters * args.batch_size / (time.time() - t0)

    print("calib_mode=%s  top1 agreement=%.3f" % (args.calib_mode, agree))
    print("fp32: %.1f img/s   int8: %.1f img/s" % (fp32_ips, int8_ips))


if __name__ == "__main__":
    main()
