"""Horovod-style data-parallel training (parity target: reference
example/distributed_training-horovod/train_mnist.py).

The script follows the exact hvd workflow — rank/size, parameter
broadcast from rank 0, allreduce-averaged gradients — through the
kvstore='horovod' adapter when a horovod package is present, and falls
back to the framework's native path (kvstore='tpu_ici', XLA collectives
over the mesh) otherwise, so the same script runs everywhere.

Run: python example/distributed_training-horovod/train_mnist_hvd.py [--smoke]
"""
import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import np as np
from mxnet_tpu.gluon import nn


def make_kvstore():
    try:
        kv = mx.kv.create("horovod")
        print("using horovod kvstore (rank %d/%d)"
              % (kv.rank, kv.num_workers))
    except ImportError:
        kv = mx.kv.create("tpu_ici")
        print("horovod not installed; native tpu_ici collectives "
              "(rank %d/%d)" % (kv.rank, kv.num_workers))
    return kv


def build_net():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(10, 5, activation="tanh"), nn.MaxPool2D(2),
            nn.Conv2D(20, 5, activation="tanh"), nn.MaxPool2D(2),
            nn.Flatten(), nn.Dense(50, activation="tanh"), nn.Dense(10))
    return net


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()

    mx.random.seed(0)
    kv = make_kvstore()
    net = build_net()
    net.initialize(mx.init.Xavier())
    net.hybridize()

    # hvd-style: scale lr by world size, average grads across workers
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr * kv.num_workers},
                            kvstore=kv, update_on_kvstore=False)

    ds = gluon.data.vision.MNIST(train=True)
    tf = gluon.data.vision.transforms.ToTensor()
    loader = gluon.data.DataLoader(ds.transform_first(tf),
                                   batch_size=args.batch, shuffle=True)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    steps = 20 if args.smoke else None
    for epoch in range(1 if args.smoke else args.epochs):
        metric = gluon.metric.Accuracy()
        for i, (x, y) in enumerate(loader):
            if steps is not None and i >= steps:
                break
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(args.batch)
            metric.update([y], [out])
        print("epoch %d  rank %d  accuracy %.3f"
              % (epoch, kv.rank, metric.get()[1]))
    print("done")


if __name__ == "__main__":
    main()
