#!/usr/bin/env python
"""AMP model conversion (parity: reference
example/automatic-mixed-precision/amp_model_conversion.py).

Converts a model-zoo network to bfloat16 compute (the MXU-native AMP
dtype — no loss scaling needed, unlike the reference's fp16 flow) and
compares outputs/throughput against fp32.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import amp, gluon
from mxnet_tpu import np as mxnp
from mxnet_tpu.gluon.model_zoo.vision import get_model


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet18_v1")
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--image-shape", type=int, default=32)
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    mx.random.seed(0)
    net = get_model(args.model, classes=10)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = mxnp.random.uniform(
        size=(args.batch_size, 3, args.image_shape, args.image_shape))
    ref = net(x)
    ref.wait_to_read()

    amp.init(target_dtype="bfloat16")
    amp_net = amp.convert_hybrid_block(net, target_dtype="bfloat16")
    out = amp_net(x)
    out.wait_to_read()

    rel = (onp.abs(out.asnumpy().astype(onp.float32) - ref.asnumpy()).max()
           / (onp.abs(ref.asnumpy()).max() + 1e-9))
    print("bf16 vs fp32 max relative deviation: %.4f" % rel)

    for name, model in (("fp32", net), ("bf16", amp_net)):
        model(x).wait_to_read()  # warm
        tic = time.time()
        for _ in range(args.iters):
            model(x).wait_to_read()
        dur = time.time() - tic
        print("%s: %.1f img/s" % (name,
                                  args.iters * args.batch_size / dur))


if __name__ == "__main__":
    main()
