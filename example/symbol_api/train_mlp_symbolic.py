"""Reference-style symbolic workflow on the TPU-native stack.

Mirrors the classic MXNet symbolic script shape (reference
example/image-classification/train_mnist.py with mx.sym): compose a
graph from sym.var + legacy ops, simple_bind, run forward/backward with
the Executor, then export — both to the StableHLO deployment artifact
(SymbolBlock.imports) and to ONNX (contrib.onnx), plus a subgraph
partition pass.

Run: python example/symbol_api/train_mlp_symbolic.py
"""
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import np as mxnp  # noqa: E402
from mxnet_tpu import sym  # noqa: E402
from mxnet_tpu.contrib.onnx import export_to_model_dict  # noqa: E402
from mxnet_tpu.subgraph import partition_symbol  # noqa: E402


def main():
    rng = onp.random.RandomState(0)

    # -- compose ----------------------------------------------------------
    data = sym.var("data", shape=(64, 20), dtype="float32")
    net = sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = sym.Activation(net, act_type="relu", name="a1")
    net = sym.FullyConnected(net, num_hidden=2, name="fc2")
    print("arguments:", net.list_arguments())
    args, outs, _ = net.infer_shape(data=(64, 20))
    print("inferred shapes:", dict(zip(net.list_arguments(), args)))

    # -- bind + train a few SGD steps -------------------------------------
    ex = net.simple_bind(data=(64, 20))
    for k in ex.arg_dict:
        if k != "data":
            ex.arg_dict[k] = mxnp.array(
                (rng.randn(*ex.arg_dict[k].shape) * 0.1).astype("float32"))
    w_true = rng.randn(20, 2).astype("float32")
    for step in range(20):
        x = rng.randn(64, 20).astype("float32")
        y = x @ w_true
        ex.arg_dict["data"] = mxnp.array(x)
        (out,) = ex.forward(is_train=True)
        grad_out = 2 * (out.asnumpy() - y) / y.size
        ex.backward(mxnp.array(grad_out))
        for k, g in ex.grad_dict.items():
            if k != "data":
                ex.arg_dict[k] = mxnp.array(
                    ex.arg_dict[k].asnumpy() - 0.5 * g.asnumpy())
        if step % 5 == 0:
            loss = float(((out.asnumpy() - y) ** 2).mean())
            print("step %2d  mse %.4f" % (step, loss))

    # -- partition (reference optimize_for / BuildSubgraph) ---------------
    part = partition_symbol(net, {"legacy:FullyConnected",
                                  "legacy:Activation"})
    n_sub = sum(1 for n in part._topo() if n._kind == "subgraph")
    print("partitioned into %d subgraph node(s)" % n_sub)

    # -- export: ONNX model dict + StableHLO artifact ---------------------
    params = {k: v for k, v in ex.arg_dict.items() if k != "data"}
    model = export_to_model_dict(net, params)
    print("onnx nodes:", [n["op_type"] for n in model["graph"]["node"]])
    art, pvals = net.export_artifact(params)
    art.save("/tmp/mlp-symbol.json")
    onp.savez("/tmp/mlp-0000.params.npz",
              **{k: onp.asarray(v) for k, v in pvals.items()})
    from mxnet_tpu.gluon import SymbolBlock
    blk = SymbolBlock.imports("/tmp/mlp-symbol.json", ["data"],
                              "/tmp/mlp-0000.params.npz")
    x = rng.randn(64, 20).astype("float32")
    ex.arg_dict["data"] = mxnp.array(x)
    (ref,) = ex.forward()
    onp.testing.assert_allclose(blk(mxnp.array(x)).asnumpy(),
                                ref.asnumpy(), rtol=1e-4, atol=1e-4)
    print("artifact round-trip OK")


if __name__ == "__main__":
    main()
