#!/usr/bin/env python
"""Urban-sounds-style audio classification (parity:
example/gluon/audio/urban_sounds — MFCC-like spectral features into an
MLP, reference model.py get_net: Dense(256)-Dense(256)-Dense(labels)).

Offline-friendly: synthesizes labeled waveforms (each class = a band of
sinusoid frequencies + noise), computes log-mel-style filterbank
features with the framework's own ops (the reference leans on librosa
MFCCs), and trains the reference MLP.

Run:  python example/gluon/audio_classification.py --steps 30
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import np as mxnp, autograd, gluon
from mxnet_tpu.gluon import nn

NUM_LABELS = 10
SR = 4000
DUR = 0.5


def get_net(num_labels=NUM_LABELS):
    """Reference example/gluon/audio/urban_sounds/model.py get_net."""
    net = nn.Sequential()
    net.add(nn.Dense(256, activation="relu"),
            nn.Dense(256, activation="relu"),
            nn.Dense(num_labels))
    net.initialize(mx.init.Xavier())
    return net


def synth_wave(rng, label):
    n = int(SR * DUR)
    t = onp.arange(n) / SR
    f0 = 120.0 * (label + 1)
    wave = (onp.sin(2 * onp.pi * f0 * t)
            + 0.5 * onp.sin(2 * onp.pi * 2 * f0 * t))
    return (wave + 0.3 * rng.randn(n)).astype("float32")


def filterbank_features(waves, n_fft=256, hop=128, n_bands=26):
    """Log filterbank energies computed with mx ops (librosa-MFCC
    stand-in): frame → FFT magnitude (via matmul against a DFT basis —
    einsum rides the MXU) → triangular band pooling → log."""
    b, n = waves.shape
    frames = []
    for start in range(0, n - n_fft + 1, hop):
        frames.append(waves[:, start:start + n_fft])
    f = mxnp.stack(frames, axis=1)  # (B, F, n_fft)
    k = onp.arange(n_fft)
    basis_r = onp.cos(-2 * onp.pi * onp.outer(k, k) / n_fft)
    basis_i = onp.sin(-2 * onp.pi * onp.outer(k, k) / n_fft)
    br = mxnp.array(basis_r[:, :n_fft // 2].astype("float32"))
    bi = mxnp.array(basis_i[:, :n_fft // 2].astype("float32"))
    re = mxnp.einsum("bfn,nk->bfk", f, br)
    im = mxnp.einsum("bfn,nk->bfk", f, bi)
    mag = mxnp.sqrt(re * re + im * im + 1e-8)
    # triangular bands over the magnitude bins
    nb = n_fft // 2
    edges = onp.linspace(0, nb, n_bands + 2).astype(int)
    bands = onp.zeros((nb, n_bands), dtype="float32")
    for j in range(n_bands):
        lo, mid, hi = edges[j], edges[j + 1], edges[j + 2]
        if mid > lo:
            bands[lo:mid, j] = onp.linspace(0, 1, mid - lo)
        if hi > mid:
            bands[mid:hi, j] = onp.linspace(1, 0, hi - mid)
    fb = mxnp.einsum("bfk,kj->bfj", mag, mxnp.array(bands))
    feats = mxnp.log(fb + 1e-6)
    return feats.reshape(b, -1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CI run")
    args = ap.parse_args()
    if args.smoke:
        args.steps = 8

    mx.random.seed(0)
    rng = onp.random.RandomState(0)
    net = get_net()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})

    losses, accs = [], []
    for step in range(args.steps):
        labels = rng.randint(0, NUM_LABELS, size=args.batch)
        waves = mxnp.array(onp.stack([synth_wave(rng, l) for l in labels]))
        feats = filterbank_features(waves)
        y = mxnp.array(labels.astype("float32"))
        with autograd.record():
            out = net(feats)
            loss = loss_fn(out, y)
        loss.backward()
        trainer.step(args.batch)
        losses.append(float(loss.mean().asnumpy()))
        accs.append(float((out.asnumpy().argmax(1) == labels).mean()))
    print("audio loss %.3f -> %.3f, acc %.2f -> %.2f"
          % (losses[0], losses[-1], accs[0], accs[-1]))
    if not args.smoke:
        assert losses[-1] < losses[0], "loss did not decrease"
    print("done")


if __name__ == "__main__":
    main()
