"""DCGAN (parity target: reference example/gluon/dc_gan) — TPU-native:
both networks hybridize into single XLA programs; one fused
generator+discriminator update per step.

Synthetic 32x32 image data keeps the example offline; swap `real_batch`
for an ImageRecordIter / DataLoader stream for real training.

Run: python example/gluon/dc_gan.py [--iters N] [--smoke]
"""
import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import np as np
from mxnet_tpu.gluon import nn


def build_generator(ngf=32, nz=64):
    net = nn.HybridSequential()
    net.add(
        nn.Conv2DTranspose(ngf * 4, 4, 1, 0, use_bias=False),  # 1 -> 4
        nn.BatchNorm(), nn.Activation("relu"),
        nn.Conv2DTranspose(ngf * 2, 4, 2, 1, use_bias=False),  # 4 -> 8
        nn.BatchNorm(), nn.Activation("relu"),
        nn.Conv2DTranspose(ngf, 4, 2, 1, use_bias=False),      # 8 -> 16
        nn.BatchNorm(), nn.Activation("relu"),
        nn.Conv2DTranspose(1, 4, 2, 1, use_bias=False),        # 16 -> 32
        nn.Activation("tanh"))
    return net


def build_discriminator(ndf=32):
    net = nn.HybridSequential()
    net.add(
        nn.Conv2D(ndf, 4, 2, 1, use_bias=False),
        nn.LeakyReLU(0.2),
        nn.Conv2D(ndf * 2, 4, 2, 1, use_bias=False),
        nn.BatchNorm(), nn.LeakyReLU(0.2),
        nn.Conv2D(ndf * 4, 4, 2, 1, use_bias=False),
        nn.BatchNorm(), nn.LeakyReLU(0.2),
        nn.Conv2D(1, 4, 1, 0, use_bias=False))
    return net


def real_batch(rng, batch):
    """Synthetic 'real' distribution: soft blobs (stands in for MNIST)."""
    yy, xx = onp.mgrid[0:32, 0:32] / 32.0          # (32, 32) each
    cx = rng.uniform(0.25, 0.75, (batch, 1, 1))
    cy = rng.uniform(0.25, 0.75, (batch, 1, 1))
    img = onp.exp(-(((xx - cx) ** 2 + (yy - cy) ** 2) / 0.02)) * 2 - 1
    return np.array(img[:, None].astype("float32"))  # (B, 1, 32, 32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--nz", type=int, default=64)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        args.iters, args.batch = 4, 8

    mx.random.seed(0)
    rng = onp.random.RandomState(0)
    netG, netD = build_generator(nz=args.nz), build_discriminator()
    netG.initialize(mx.init.Normal(0.02))
    netD.initialize(mx.init.Normal(0.02))
    netG.hybridize()
    netD.hybridize()
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    trainerG = gluon.Trainer(netG.collect_params(), "adam",
                             {"learning_rate": 2e-4, "beta1": 0.5})
    trainerD = gluon.Trainer(netD.collect_params(), "adam",
                             {"learning_rate": 2e-4, "beta1": 0.5})

    ones = np.ones((args.batch,))
    zeros = np.zeros((args.batch,))
    for it in range(args.iters):
        real = real_batch(rng, args.batch)
        noise = np.random.normal(0, 1, size=(args.batch, args.nz, 1, 1))
        # D step
        with autograd.record():
            fake = netG(noise)
            errD = (loss_fn(netD(real).reshape((-1,)), ones)
                    + loss_fn(netD(fake.detach()).reshape((-1,)), zeros))
            errD = errD.mean()
        errD.backward()
        trainerD.step(1)
        # G step
        with autograd.record():
            errG = loss_fn(netD(netG(noise)).reshape((-1,)), ones).mean()
        errG.backward()
        trainerG.step(1)
        if it % max(1, args.iters // 10) == 0 or it == args.iters - 1:
            print("iter %d  D=%.4f  G=%.4f"
                  % (it, float(errD.asnumpy()), float(errG.asnumpy())))
    print("done")


if __name__ == "__main__":
    main()
