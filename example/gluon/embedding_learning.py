"""Deep metric / embedding learning (parity target: reference
example/gluon/embedding_learning — margin-based loss with distance
weighted sampling).  TPU-native: the whole batch's pairwise-distance
matrix and the sampling weights compute in one fused program.

Synthetic class clusters stand in for CUB200 so the example is offline.

Run: python example/gluon/embedding_learning.py [--iters N] [--smoke]
"""
import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import np as np
from mxnet_tpu.gluon import nn


def synthetic_classes(rng, n_classes=8, dim=32):
    return rng.randn(n_classes, dim).astype("float32") * 3.0


def sample_batch(rng, centers, per_class=4, noise=0.5):
    n_classes, dim = centers.shape
    ids = rng.choice(n_classes, 4, replace=False)
    x = onp.concatenate([
        centers[c] + rng.randn(per_class, dim).astype("float32") * noise
        for c in ids])
    y = onp.repeat(ids, per_class)
    return x.astype("float32"), y.astype("int32")


class MarginNet(gluon.HybridBlock):
    def __init__(self, embed_dim=16):
        super().__init__()
        self.body = nn.HybridSequential()
        self.body.add(nn.Dense(64, activation="relu"),
                      nn.Dense(embed_dim))

    def forward(self, x):
        e = self.body(x)
        return e / (np.sqrt((e ** 2).sum(axis=1, keepdims=True)) + 1e-8)


def margin_loss(emb, labels, margin=0.2, beta=1.2):
    """Margin-based loss over all positive/negative pairs in the batch
    (reference MarginLoss, vectorized: no per-pair python loops)."""
    d = np.sqrt(((emb.expand_dims(1) - emb.expand_dims(0)) ** 2)
                .sum(axis=-1) + 1e-8)
    same = (labels.expand_dims(1) == labels.expand_dims(0))
    eye = np.eye(emb.shape[0])
    pos = same * (1 - eye)
    neg = 1 - same
    pos_loss = np.maximum(d - beta + margin, 0.0) * pos
    neg_loss = np.maximum(beta - d + margin, 0.0) * neg
    pair_cnt = np.maximum((pos_loss > 0).sum() + (neg_loss > 0).sum(), 1)
    return (pos_loss.sum() + neg_loss.sum()) / pair_cnt


def retrieval_accuracy(emb, labels):
    d = ((emb[:, None, :] - emb[None, :, :]) ** 2).sum(-1)
    onp.fill_diagonal(d, onp.inf)
    nn_idx = d.argmin(1)
    return float((labels[nn_idx] == labels).mean())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=300)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        args.iters = 10

    mx.random.seed(0)
    rng = onp.random.RandomState(0)
    centers = synthetic_classes(rng)
    net = MarginNet()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})

    for it in range(args.iters):
        xb, yb = sample_batch(rng, centers)
        x, y = np.array(xb), np.array(yb)
        with autograd.record():
            loss = margin_loss(net(x), y)
        loss.backward()
        trainer.step(x.shape[0])
        if it % max(1, args.iters // 10) == 0 or it == args.iters - 1:
            print("iter %d  loss %.4f" % (it, float(loss.asnumpy())))

    # recall@1 on a held-out batch
    xe, ye = sample_batch(rng, centers, per_class=8)
    acc = retrieval_accuracy(net(np.array(xe)).asnumpy(), ye)
    print("nearest-neighbor retrieval accuracy: %.2f" % acc)


if __name__ == "__main__":
    main()
