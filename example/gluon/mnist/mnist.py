#!/usr/bin/env python
"""MNIST training (parity: reference example/gluon/mnist/mnist.py —
BASELINE config #1: the minimum end-to-end slice).

Usage: python example/gluon/mnist/mnist.py [--epochs 3] [--hybridize]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


def build_net(hybridize):
    net = nn.HybridSequential()
    net.add(nn.Dense(128, activation="relu"),
            nn.Dense(64, activation="relu"),
            nn.Dense(10))
    net.initialize(mx.init.Xavier())
    if hybridize:
        net.hybridize()
    return net


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=100)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--hybridize", action="store_true")
    ap.add_argument("--max-batches", type=int, default=0,
                    help="truncate epochs (smoke testing)")
    args = ap.parse_args()

    tf = gluon.data.vision.transforms.ToTensor()
    train_data = gluon.data.DataLoader(
        gluon.data.vision.MNIST(train=True).transform_first(tf),
        batch_size=args.batch_size, shuffle=True)
    val_data = gluon.data.DataLoader(
        gluon.data.vision.MNIST(train=False).transform_first(tf),
        batch_size=args.batch_size)

    net = build_net(args.hybridize)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    metric = gluon.metric.Accuracy()

    for epoch in range(args.epochs):
        metric.reset()
        tic = time.time()
        for i, (x, y) in enumerate(train_data):
            if args.max_batches and i >= args.max_batches:
                break
            x = x.reshape(x.shape[0], -1)
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(x.shape[0])
            metric.update(y, out)
        name, acc = metric.get()
        print("Epoch %d: train %s=%.4f (%.1fs)" % (
            epoch, name, acc, time.time() - tic))

    metric.reset()
    for i, (x, y) in enumerate(val_data):
        if args.max_batches and i >= args.max_batches:
            break
        metric.update(y, net(x.reshape(x.shape[0], -1)))
    print("Validation %s=%.4f" % metric.get())


if __name__ == "__main__":
    main()
