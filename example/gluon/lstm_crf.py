"""BiLSTM-CRF sequence tagger (parity target: reference
example/gluon/lstm_crf) — TPU-native: the CRF forward algorithm and
Viterbi decode are vectorized over the tag dimension (logsumexp /
max-reduction per step instead of the reference's per-tag python loops).

Tiny in-file corpus; the point is the model, not the data.

Run: python example/gluon/lstm_crf.py [--epochs N] [--smoke]
"""
import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import np as np
from mxnet_tpu.gluon import nn, rnn

START, STOP = "<s>", "</s>"


class BiLSTMCRF(gluon.Block):
    def __init__(self, vocab_size, tag2idx, embed=32, hidden=32):
        super().__init__()
        self.tag2idx = tag2idx
        self.n_tags = len(tag2idx)
        self.embedding = nn.Embedding(vocab_size, embed)
        self.lstm = rnn.LSTM(hidden // 2, bidirectional=True,
                             layout="NTC", input_size=embed)
        self.hidden2tag = nn.Dense(self.n_tags, flatten=False,
                                   in_units=hidden)
        self.transitions = gluon.Parameter("transitions",
                                           shape=(self.n_tags, self.n_tags))

    def _emissions(self, sent):
        h = self.lstm(self.embedding(sent.reshape((1, -1))))
        return self.hidden2tag(h)[0]  # (T, n_tags)

    def _forward_alg(self, emis):
        """log Z via the forward algorithm, vectorized over tags."""
        T = self.transitions.data()
        alpha = np.full((self.n_tags,), -10000.0)
        alpha[self.tag2idx[START]] = 0.0
        for t in range(emis.shape[0]):
            # broadcast: alpha[j] + T[i, j] + emis[t, i]
            scores = alpha.reshape((1, -1)) + T + \
                emis[t].reshape((-1, 1))
            m = scores.max(axis=1, keepdims=True)
            alpha = (m.reshape((-1,))
                     + np.log(np.exp(scores - m).sum(axis=1)))
        final = alpha + T[self.tag2idx[STOP]]
        m = final.max()
        return m + np.log(np.exp(final - m).sum())

    def _score(self, emis, tags):
        T = self.transitions.data()
        idx = [self.tag2idx[START]] + tags
        s = np.array(0.0)
        for t in range(emis.shape[0]):
            s = s + T[idx[t + 1], idx[t]] + emis[t, idx[t + 1]]
        return s + T[self.tag2idx[STOP], idx[-1]]

    def neg_log_likelihood(self, sent, tags):
        emis = self._emissions(sent)
        return self._forward_alg(emis) - self._score(emis, tags)

    def viterbi(self, sent):
        emis = self._emissions(sent).asnumpy()
        T = self.transitions.data().asnumpy()
        alpha = onp.full(self.n_tags, -10000.0)
        alpha[self.tag2idx[START]] = 0.0
        back = []
        for t in range(emis.shape[0]):
            scores = alpha[None, :] + T          # (to, from)
            best = scores.argmax(1)
            alpha = scores.max(1) + emis[t]
            back.append(best)
        final = alpha + T[self.tag2idx[STOP]]
        path = [int(final.argmax())]
        for bptr in reversed(back):
            path.append(int(bptr[path[-1]]))
        path.reverse()
        return path[1:], float(final.max())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=40)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        args.epochs = 3

    data = [
        ("the wall street journal reported today that apple corporation "
         "made money".split(), "B I I I O O O B I O O".split()),
        ("georgia tech is a university in georgia".split(),
         "B I O O O O B".split()),
    ]
    vocab = {w: i for i, w in enumerate(
        sorted({w for s, _ in data for w in s}))}
    tag2idx = {"B": 0, "I": 1, "O": 2, START: 3, STOP: 4}

    mx.random.seed(0)
    model = BiLSTMCRF(len(vocab), tag2idx)
    model.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "sgd",
                            {"learning_rate": 0.01, "wd": 1e-4})

    def encode(sent):
        return np.array(onp.array([vocab[w] for w in sent], "int32"))

    first = last = None
    for ep in range(args.epochs):
        total = 0.0
        for sent, tags in data:
            with autograd.record():
                nll = model.neg_log_likelihood(
                    encode(sent), [tag2idx[t] for t in tags])
            nll.backward()
            trainer.step(1)
            total += float(nll.asnumpy())
        if first is None:
            first = total
        last = total
        if ep % 10 == 0 or ep == args.epochs - 1:
            print("epoch %d  nll %.3f" % (ep, total))

    path, score = model.viterbi(encode(data[0][0]))
    inv = {v: k for k, v in tag2idx.items()}
    print("viterbi:", [inv[p] for p in path], "score %.2f" % score)
    assert last < first, "training did not reduce NLL"


if __name__ == "__main__":
    main()
