#!/usr/bin/env python
"""LipNet: lip-reading from video with CTC (parity:
example/gluon/lipnet — STCNN (Conv3D+norm+pool) stacks into a
bidirectional GRU and a per-frame character classifier trained with CTC
loss; greedy CTC decoding at the end).

Offline-friendly: trains on a synthetic lip-video dataset (moving-bar
"mouths" labeled with short character sequences) so the pipeline —
Conv3D video stem, time-major GRU, CTC alignment, greedy decode — runs
end-to-end without the GRID corpus.

Run:  python example/gluon/lipnet.py --steps 12
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import np as mxnp, npx, autograd, gluon
from mxnet_tpu.gluon import nn, rnn

ALPHABET = " abcdefghij"  # index 0 = CTC blank
VOCAB = len(ALPHABET)


class LipNet(gluon.HybridBlock):
    """STCNN x3 → BiGRU x2 → per-frame character logits
    (reference models/network.py LipNet, thinned for the synthetic
    task; same layer families: Conv3D, norm, dropout, MaxPool3D, GRU)."""

    def __init__(self, dr_rate=0.2, hidden=48):
        super().__init__()
        self.conv1 = nn.Conv3D(8, kernel_size=(3, 5, 5), strides=(1, 2, 2),
                               padding=(1, 2, 2))
        self.bn1 = nn.BatchNorm(axis=1)
        self.pool1 = nn.MaxPool3D((1, 2, 2), (1, 2, 2))
        self.conv2 = nn.Conv3D(16, kernel_size=(3, 3, 3),
                               padding=(1, 1, 1))
        self.bn2 = nn.BatchNorm(axis=1)
        self.pool2 = nn.MaxPool3D((1, 2, 2), (1, 2, 2))
        self.dropout = nn.Dropout(dr_rate)
        self.gru = rnn.GRU(hidden, num_layers=2, bidirectional=True,
                           layout="NTC")
        self.fc = nn.Dense(VOCAB, flatten=False)

    def forward(self, x):
        # x: (B, C=1, T, H, W) video
        h = npx.relu(self.bn1(self.conv1(x)))
        h = self.pool1(h)
        h = npx.relu(self.bn2(self.conv2(h)))
        h = self.pool2(h)
        h = self.dropout(h)
        # (B, C, T, H, W) → (B, T, C*H*W) frame features
        b, c, t = h.shape[0], h.shape[1], h.shape[2]
        h = h.transpose(0, 2, 1, 3, 4).reshape(b, t, -1)
        h = self.gru(h)
        return self.fc(h)  # (B, T, VOCAB)


def synthetic_batch(rng, batch, T=12, hw=32, max_label=4):
    """Moving-bar videos; the bar's row selects the character."""
    x = onp.zeros((batch, 1, T, hw, hw), dtype="float32")
    labels = onp.zeros((batch, max_label), dtype="float32")
    for i in range(batch):
        chars = rng.randint(1, VOCAB, size=max_label)
        labels[i] = chars
        for j, ch in enumerate(chars):
            t0 = j * (T // max_label)
            row = int((ch / VOCAB) * (hw - 4))
            for t in range(t0, min(t0 + T // max_label, T)):
                x[i, 0, t, row:row + 4, :] = 1.0
    return mxnp.array(x), mxnp.array(labels)


def ctc_greedy_decode(logits):
    """Best-path CTC decode (reference BeamSearch.py is the beam
    variant; greedy is the smoke-test decoder)."""
    best = logits.asnumpy().argmax(-1)
    outs = []
    for seq in best:
        prev, chars = -1, []
        for s in seq:
            if s != prev and s != 0:
                chars.append(ALPHABET[s])
            prev = s
        outs.append("".join(chars))
    return outs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CI run")
    args = ap.parse_args()
    if args.smoke:
        args.steps = 6

    mx.random.seed(0)
    rng = onp.random.RandomState(0)
    net = LipNet()
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.CTCLoss(layout="NTC", label_layout="NT")
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})

    losses = []
    for step in range(args.steps):
        x, y = synthetic_batch(rng, args.batch)
        with autograd.record():
            logits = net(x)
            loss = loss_fn(logits, y)
        loss.backward()
        trainer.step(args.batch)
        losses.append(float(loss.mean().asnumpy()))
    print("lipnet ctc loss: %.3f -> %.3f" % (losses[0], losses[-1]))
    x, y = synthetic_batch(rng, 2)
    print("greedy decode sample:", ctc_greedy_decode(net(x))[:2])
    if not args.smoke:
        assert losses[-1] < losses[0], "CTC loss did not decrease"
    print("done")


if __name__ == "__main__":
    main()
