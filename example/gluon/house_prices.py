"""Kaggle-house-prices-style tabular regression (parity target:
reference example/gluon/house_prices) — standardized features, MLP with
dropout, log-RMSE metric, k-fold CV.  Synthetic data generator stands in
for the Kaggle CSVs so the example runs offline; point --train-csv at
the real file to reproduce the original.

Run: python example/gluon/house_prices.py [--epochs N] [--smoke]
"""
import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import np as np
from mxnet_tpu.gluon import nn


def synthetic_houses(n=1024, d=40, seed=0):
    rng = onp.random.RandomState(seed)
    X = rng.randn(n, d).astype("float32")
    w = rng.randn(d) * rng.binomial(1, 0.4, d)  # sparse ground truth
    logp = X @ w * 0.1 + 12 + rng.randn(n) * 0.1
    return X, onp.exp(logp).astype("float32")


def log_rmse(net, X, y):
    pred = np.clip(net(X), 1.0, None)
    return float(np.sqrt(((np.log(pred.reshape((-1,))) - np.log(y)) ** 2)
                         .mean()).asnumpy())


def build_net(dropout=0.1):
    net = nn.HybridSequential()
    net.add(nn.Dense(128, activation="relu"), nn.Dropout(dropout),
            nn.Dense(64, activation="relu"), nn.Dropout(dropout),
            nn.Dense(1))
    return net


def train_fold(Xtr, ytr, Xva, yva, epochs, lr, wd, batch):
    net = build_net()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr, "wd": wd})
    l2 = gluon.loss.L2Loss()
    ds = gluon.data.ArrayDataset(Xtr, ytr)
    loader = gluon.data.DataLoader(ds, batch_size=batch, shuffle=True)
    for _ in range(epochs):
        for xb, yb in loader:
            with autograd.record():
                loss = l2(net(xb).reshape((-1,)), np.log(yb))
            loss.backward()
            trainer.step(batch)
    # the head predicts log-price; undo for the metric
    class Exp(gluon.HybridBlock):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def forward(self, x):
            return np.exp(self.inner(x))
    return log_rmse(Exp(net), Xva, yva)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=20)
    ap.add_argument("--folds", type=int, default=4)
    ap.add_argument("--lr", type=float, default=5e-3)
    ap.add_argument("--wd", type=float, default=1e-4)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        args.epochs, args.folds = 2, 2

    mx.random.seed(0)
    X, y = synthetic_houses()
    # standardize features (the reference preprocesses the same way)
    X = (X - X.mean(0)) / (X.std(0) + 1e-6)
    Xn, yn = np.array(X), np.array(y)

    n = X.shape[0]
    fold = n // args.folds
    scores = []
    for k in range(args.folds):
        lo, hi = k * fold, (k + 1) * fold
        idx_va = onp.arange(lo, hi)
        idx_tr = onp.concatenate([onp.arange(0, lo), onp.arange(hi, n)])
        rmse = train_fold(Xn[np.array(idx_tr)], yn[np.array(idx_tr)],
                          Xn[np.array(idx_va)], yn[np.array(idx_va)],
                          args.epochs, args.lr, args.wd, args.batch)
        scores.append(rmse)
        print("fold %d  log-rmse %.4f" % (k, rmse))
    print("cv log-rmse: %.4f" % (sum(scores) / len(scores)))


if __name__ == "__main__":
    main()
