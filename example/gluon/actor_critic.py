"""Actor-critic policy gradient (parity target: reference
example/gluon/actor_critic) — TPU-native: the policy/value net
hybridizes; episodes run imperatively (the env is host-side Python).

A dependency-free CartPole implementation replaces gym so the example
runs offline.

Run: python example/gluon/actor_critic.py [--episodes N] [--smoke]
"""
import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import np as np
from mxnet_tpu.gluon import nn


class CartPole:
    """Classic cart-pole dynamics (Barto et al.), numpy only."""

    def __init__(self, seed=0):
        self.rng = onp.random.RandomState(seed)

    def reset(self):
        self.s = self.rng.uniform(-0.05, 0.05, 4)
        self.t = 0
        return self.s.copy()

    def step(self, action):
        x, xd, th, thd = self.s
        f = 10.0 if action == 1 else -10.0
        costh, sinth = onp.cos(th), onp.sin(th)
        temp = (f + 0.05 * thd ** 2 * sinth) / 1.1
        thacc = (9.8 * sinth - costh * temp) / \
            (0.5 * (4.0 / 3.0 - 0.1 * costh ** 2 / 1.1))
        xacc = temp - 0.05 * thacc * costh / 1.1
        tau = 0.02
        self.s = onp.array([x + tau * xd, xd + tau * xacc,
                            th + tau * thd, thd + tau * thacc])
        self.t += 1
        done = bool(abs(self.s[0]) > 2.4 or abs(self.s[2]) > 0.2095
                    or self.t >= 200)
        return self.s.copy(), 1.0, done


class PolicyValue(gluon.HybridBlock):
    def __init__(self):
        super().__init__()
        self.body = nn.Dense(128, activation="relu", in_units=4)
        self.action = nn.Dense(2, in_units=128)
        self.value = nn.Dense(1, in_units=128)

    def forward(self, x):
        h = self.body(x)
        return self.action(h), self.value(h)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--episodes", type=int, default=150)
    ap.add_argument("--gamma", type=float, default=0.99)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        args.episodes = 3

    mx.random.seed(0)
    env = CartPole(seed=0)
    net = PolicyValue()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    rng = onp.random.RandomState(1)

    running = 10.0
    for ep in range(args.episodes):
        s = env.reset()
        states, actions, rewards = [], [], []
        done = False
        while not done:
            logits, _ = net(np.array(s[None].astype("float32")))
            p = onp.exp(logits.asnumpy()[0])
            p = p / p.sum()
            a = int(rng.choice(2, p=p))
            states.append(s)
            actions.append(a)
            s, r, done = env.step(a)
            rewards.append(r)

        # discounted returns, normalized
        R, returns = 0.0, []
        for r in reversed(rewards):
            R = r + args.gamma * R
            returns.append(R)
        returns = onp.array(returns[::-1], "float32")
        returns = (returns - returns.mean()) / (returns.std() + 1e-6)

        S = np.array(onp.stack(states).astype("float32"))
        A = np.array(onp.array(actions, "int32"))
        G = np.array(returns)
        with autograd.record():
            logits, values = net(S)
            logp = mx.npx.log_softmax(logits, axis=-1)
            chosen = mx.npx.pick(logp, A, axis=-1)
            adv = (G - values.reshape((-1,))).detach()
            policy_loss = -(chosen * adv).sum()
            value_loss = ((values.reshape((-1,)) - G) ** 2).sum()
            loss = policy_loss + 0.5 * value_loss
        loss.backward()
        trainer.step(len(rewards))

        running = 0.95 * running + 0.05 * len(rewards)
        if ep % 10 == 0 or ep == args.episodes - 1:
            print("episode %d  length %d  running %.1f"
                  % (ep, len(rewards), running))
    print("done")


if __name__ == "__main__":
    main()
