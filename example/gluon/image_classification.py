#!/usr/bin/env python
"""Image classification with model-zoo networks (parity: reference
example/gluon/image_classification.py — BASELINE configs #2/#4 seed).

Usage:
  python example/gluon/image_classification.py --model resnet18_v1 \
      --dataset synthetic --batch-size 32 --epochs 1 --kvstore device
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import np as mxnp
from mxnet_tpu.gluon.model_zoo.vision import get_model


def get_data(args):
    if args.dataset == "synthetic":
        rng = onp.random.RandomState(0)
        n = args.batch_size * max(args.max_batches or 8, 1)
        x = rng.rand(n, 3, args.image_shape, args.image_shape) \
            .astype(onp.float32)
        y = rng.randint(0, args.classes, n).astype(onp.float32)
        ds = gluon.data.ArrayDataset(mxnp.array(x), mxnp.array(y))
        return gluon.data.DataLoader(ds, batch_size=args.batch_size,
                                     shuffle=True)
    if args.dataset == "cifar10":
        tf = gluon.data.vision.transforms.ToTensor()
        return gluon.data.DataLoader(
            gluon.data.vision.CIFAR10(train=True).transform_first(tf),
            batch_size=args.batch_size, shuffle=True)
    if args.rec:
        from mxnet_tpu import io as mio
        return mio.ImageRecordIter(
            path_imgrec=args.rec, data_shape=(3, args.image_shape,
                                              args.image_shape),
            batch_size=args.batch_size, shuffle=True, rand_mirror=True)
    raise ValueError("unknown dataset %r" % args.dataset)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet18_v1")
    ap.add_argument("--dataset", default="synthetic",
                    choices=["synthetic", "cifar10", "rec"])
    ap.add_argument("--rec", default=None, help=".rec path for --dataset rec")
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--image-shape", type=int, default=32)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--kvstore", default="device")
    ap.add_argument("--no-hybridize", action="store_true",
                    help="run eagerly instead of whole-graph XLA")
    ap.add_argument("--max-batches", type=int, default=0)
    args = ap.parse_args()

    net = get_model(args.model, classes=args.classes)
    net.initialize(mx.init.Xavier())
    if not args.no_hybridize:
        net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9,
                             "wd": 1e-4}, kvstore=args.kvstore)
    metric = gluon.metric.Accuracy()

    for epoch in range(args.epochs):
        data = get_data(args)
        metric.reset()
        tic = time.time()
        n_img = 0
        for i, batch in enumerate(data):
            if args.max_batches and i >= args.max_batches:
                break
            if isinstance(batch, (tuple, list)):
                x, y = batch
            else:
                x, y = batch.data[0], batch.label[0]
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(x.shape[0])
            metric.update(y, out)
            n_img += x.shape[0]
        mx.waitall()
        dur = time.time() - tic
        name, acc = metric.get()
        print("Epoch %d: %s=%.4f  %.1f img/s" % (epoch, name, acc,
                                                 n_img / dur))


if __name__ == "__main__":
    main()
