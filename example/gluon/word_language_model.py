"""Word-level LSTM language model (parity target: reference
example/gluon/word_language_model, 'medium' config 2x650) — TPU-native:
the stacked LSTM is ONE lax.scan kernel, the full train step compiles
into a single program via the functional trainer, and truncated BPTT
carries hidden state across segments.

A synthetic Zipf-distributed corpus keeps the example offline; feed a
tokenized file for real PTB/wikitext training.

Run: python example/gluon/word_language_model.py [--epochs N] [--smoke]
"""
import argparse
import math

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import np as np
from mxnet_tpu.gluon import nn, rnn


class RNNModel(gluon.HybridBlock):
    def __init__(self, vocab, emsize=200, nhid=200, nlayers=2, dropout=0.2):
        super().__init__()
        self.drop = nn.Dropout(dropout)
        self.encoder = nn.Embedding(vocab, emsize)
        self.lstm = rnn.LSTM(nhid, num_layers=nlayers, layout="NTC",
                             dropout=dropout, input_size=emsize)
        self.decoder = nn.Dense(vocab, flatten=False, in_units=nhid)

    def forward(self, x, state=None):
        emb = self.drop(self.encoder(x))
        if state is None:
            out = self.lstm(emb)
            return self.decoder(self.drop(out))
        out, state = self.lstm(emb, state)
        return self.decoder(self.drop(out)), state


def synthetic_corpus(n_tokens=20000, vocab=1000, seed=0):
    rng = onp.random.RandomState(seed)
    # Zipf-ish unigram with a 2-gram structure so the model has signal
    p = 1.0 / onp.arange(1, vocab + 1)
    p /= p.sum()
    toks = [int(rng.choice(vocab, p=p))]
    for _ in range(n_tokens - 1):
        prev = toks[-1]
        toks.append((prev * 31 + 7) % vocab if rng.rand() < 0.5
                    else int(rng.choice(vocab, p=p)))
    return onp.array(toks, "int32")


def batchify(corpus, batch):
    n = len(corpus) // batch
    return corpus[:n * batch].reshape(batch, n)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--bptt", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=1000)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        args.epochs = 1

    mx.random.seed(0)
    data = batchify(synthetic_corpus(vocab=args.vocab), args.batch)
    model = RNNModel(args.vocab)
    model.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "sgd",
                            {"learning_rate": 1.0})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    n_seg = (data.shape[1] - 1) // args.bptt
    if args.smoke:
        n_seg = min(n_seg, 3)
    for ep in range(args.epochs):
        total, count = 0.0, 0
        for i in range(n_seg):
            lo = i * args.bptt
            x = np.array(data[:, lo:lo + args.bptt])
            y = np.array(data[:, lo + 1:lo + args.bptt + 1])
            with autograd.record():
                logits = model(x)
                loss = loss_fn(logits.reshape((-1, args.vocab)),
                               y.reshape((-1,))).mean()
            loss.backward()
            # grad clipping, reference-style
            grads = [p.grad() for p in model.collect_params().values()
                     if p.grad_req != "null"]
            gluon.utils.clip_global_norm(grads, 0.25)
            trainer.step(1)
            total += float(loss.asnumpy()) * args.bptt
            count += args.bptt
        ppl = math.exp(total / count)
        print("epoch %d  ppl %.1f" % (ep, ppl))
    print("done")


if __name__ == "__main__":
    main()
