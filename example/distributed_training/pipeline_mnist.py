#!/usr/bin/env python
"""Pipeline-parallel MNIST training (beyond-reference capability:
SURVEY.md §2.4 notes the reference has data parallelism only; this
example trains a real model through GPipe-style pipeline parallelism
over a `pp` mesh axis).

Model (praxis pattern): replicated prologue (Flatten + input Dense),
S identical pipelined Dense stages — one per device on the `pp` axis —
and a replicated epilogue (classifier head).  Forward microbatches
stream between stages over ppermute; backward is the AD transpose;
fwd+bwd+update compile into one XLA executable.

Run on a virtual 8-device CPU mesh:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python example/distributed_training/pipeline_mnist.py --steps 30
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--stages", type=int, default=4)
    ap.add_argument("--microbatches", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (virtual mesh)")
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import np as mxnp, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import Mesh
    from mxnet_tpu.parallel.pipeline import PipelineTrainer

    S = args.stages
    devices = jax.devices()
    assert len(devices) >= S, (
        "need %d devices for %d stages (set XLA_FLAGS="
        "--xla_force_host_platform_device_count=8)" % (S, S))
    mesh = Mesh(onp.array(devices[:S]), ("pp",))

    mx.random.seed(0)
    H = args.hidden

    prologue = nn.HybridSequential()
    prologue.add(nn.Flatten(), nn.Dense(H, activation="relu",
                                        in_units=28 * 28))
    stages = []
    for _ in range(S):
        st = nn.HybridSequential()
        st.add(nn.Dense(H, activation="relu", in_units=H))
        stages.append(st)
    epilogue = nn.Dense(10, in_units=H)

    x0 = mxnp.random.uniform(size=(args.batch, 1, 28, 28))
    for blk in [prologue] + stages + [epilogue]:
        blk.initialize(mx.init.Xavier())
    h = prologue(x0)
    for st in stages:
        h = st(h)
    epilogue(h)  # finalize deferred shapes end-to-end

    loss_obj = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = PipelineTrainer(
        prologue, stages, epilogue,
        lambda out, label: loss_obj(out, label),
        "sgd", {"learning_rate": 0.05, "momentum": 0.9}, mesh,
        n_microbatches=args.microbatches)
    state = trainer.init_state()
    trainer.build_step(donate=False)

    ds = gluon.data.vision.MNIST(train=True)
    tf = gluon.data.vision.transforms.ToTensor()
    loader = gluon.data.DataLoader(ds.transform_first(tf),
                                   batch_size=args.batch, shuffle=True)

    losses = []
    t0 = time.perf_counter()
    n = 0
    for i, (x, y) in enumerate(loader):
        if i >= args.steps:
            break
        state, loss = trainer.step(state, x, y)
        losses.append(float(jax.device_get(loss)))
        n += args.batch
    dt = time.perf_counter() - t0
    print("pipeline(%d stages, %d microbatches): loss %.3f -> %.3f, "
          "%.0f img/s" % (S, args.microbatches, losses[0], losses[-1],
                          n / dt))
    assert losses[-1] < losses[0], "loss did not decrease"


if __name__ == "__main__":
    main()
