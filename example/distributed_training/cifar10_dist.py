#!/usr/bin/env python
"""Distributed data-parallel training with dist_sync kvstore (parity:
reference example/distributed_training/cifar10_dist.py).

Launch:
  python tools/launch.py -n 2 -s 1 \
      python example/distributed_training/cifar10_dist.py --epochs 1
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import np as mxnp
from mxnet_tpu.gluon.model_zoo.vision import get_model


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet18_v1")
    ap.add_argument("--batch-size", type=int, default=64,
                    help="per-worker batch size")
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--kvstore", default="dist_sync")
    ap.add_argument("--max-batches", type=int, default=8)
    args = ap.parse_args()

    kv = mx.kv.create(args.kvstore)
    print("worker %d/%d" % (kv.rank, kv.num_workers))

    mx.random.seed(42)  # identical init on every worker
    net = get_model(args.model, classes=10)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9},
                            kvstore=kv)

    # each worker trains on ITS shard (CIFAR10 when present, else synthetic)
    try:
        tf = gluon.data.vision.transforms.ToTensor()
        ds = gluon.data.vision.CIFAR10(train=True).transform_first(tf)
    except Exception:
        ds = None
    rng = onp.random.RandomState(1000 + kv.rank)

    for epoch in range(args.epochs):
        tic = time.time()
        n_img = 0
        for i in range(args.max_batches):
            if ds is not None:
                idx = rng.randint(0, len(ds), args.batch_size)
                xs = onp.stack([ds[j][0].asnumpy() for j in idx])
                ys = onp.array([float(ds[j][1]) for j in idx], onp.float32)
            else:
                xs = rng.rand(args.batch_size, 3, 32, 32).astype(onp.float32)
                ys = rng.randint(0, 10, args.batch_size).astype(onp.float32)
            x, y = mxnp.array(xs), mxnp.array(ys)
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(args.batch_size)
            n_img += args.batch_size
        mx.waitall()
        print("worker %d epoch %d: %.1f img/s (aggregate throughput = "
              "x%d workers)" % (kv.rank, epoch,
                                n_img / (time.time() - tic),
                                kv.num_workers))
    kv.barrier()
    if kv.rank == 0 and hasattr(kv, "stop_servers"):
        kv.stop_servers()


if __name__ == "__main__":
    main()
