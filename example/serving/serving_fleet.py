"""Serve a replicated fleet with mxnet_tpu.serving.fleet: supervised
replica processes + health-routing frontend + zero-downtime rollout.

What this demonstrates (the fleet half of tests/test_fleet.py, as a
runnable deployment shape):

1. launch N supervised replica processes from one model spec (models
   named by importable builder path; the supervisor health-gates them on
   /readyz, auto-restarts crashes, and the persistent compile cache
   makes every boot after the first warm);
2. put the ``Router`` in front — clients talk to ONE address and can't
   tell the fleet from a single server;
3. SIGKILL a replica mid-traffic: requests keep succeeding (router
   failover), the supervisor restores the replica, the router re-admits
   it;
4. roll out model v2 with ``fleet.rollout`` — drain one replica at a
   time, warm-before-flip, canary gate — while traffic keeps flowing;
5. scrape the fleet stats: per-replica dispatch/eject/retry counters +
   fleet p50/p95/p99.

Run::

    python example/serving/serving_fleet.py            # 3 replicas
    python example/serving/serving_fleet.py --smoke    # CI: 2 replicas
"""
import argparse
import os
import signal
import tempfile
import threading
import time

import numpy as onp

from mxnet_tpu import serving


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="fewer replicas / requests (CI lane)")
    ap.add_argument("--replicas", type=int, default=None)
    ap.add_argument("--clients", type=int, default=None)
    ap.add_argument("--seconds", type=float, default=None,
                    help="sustained-load duration per phase")
    args = ap.parse_args()

    replicas = args.replicas or (2 if args.smoke else 3)
    clients = args.clients or (2 if args.smoke else 6)
    phase_s = args.seconds or (1.5 if args.smoke else 6.0)
    in_units = 16

    cache_dir = os.path.join(tempfile.gettempdir(), "mxtpu-fleet-demo")
    spec = {"models": [{"name": "dense",
                        "builder": "mxnet_tpu.serving.replica:demo_dense",
                        "kwargs": {"units": 4, "in_units": in_units,
                                   "seed": 0},
                        "item_shape": [in_units], "max_batch_size": 8}],
            "flush_ms": 5.0, "max_queue_depth": 256}

    fleet = serving.ServingFleet(
        spec, replicas=replicas,
        env={"MXNET_COMPILE_CACHE_DIR": cache_dir},
        router_kwargs={"probe_ms": 100},
        supervisor_kwargs={"restart_backoff_ms": 100})
    t0 = time.perf_counter()
    fleet.start()
    host, port = fleet.address
    print("fleet of %d replicas up in %.1fs, router on http://%s:%d "
          "(replicas: %s)" % (replicas, time.perf_counter() - t0, host,
                              port, fleet.supervisor.addresses()))

    stop = threading.Event()
    counts = {"ok": 0, "fail": 0}
    lock = threading.Lock()

    def client_loop(cid):
        rng = onp.random.RandomState(cid)
        cli = serving.ServingClient(host, port, timeout=60, retries=0)
        while not stop.is_set():
            try:
                x = rng.rand(1, in_units).astype("float32")
                preds = cli.predict("dense", x)
                assert preds.shape == (1, 4)
                with lock:
                    counts["ok"] += 1
            except Exception as e:
                with lock:
                    counts["fail"] += 1
                print("request failed: %r" % (e,))
        cli.close()

    threads = [threading.Thread(target=client_loop, args=(c,),
                                daemon=True) for c in range(clients)]
    for t in threads:
        t.start()
    try:
        time.sleep(phase_s)
        victim = fleet.supervisor.kill(1, signal.SIGKILL)
        print("SIGKILL replica %s mid-traffic..." % victim.rid)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and \
                fleet.supervisor.ready_count() < replicas:
            time.sleep(0.2)
        print("supervisor restored %d/%d replicas"
              % (fleet.supervisor.ready_count(), replicas))

        print("rolling out v2 (scale changes) during traffic...")
        report = fleet.rollout(
            {"name": "dense",
             "builder": "mxnet_tpu.serving.replica:demo_dense",
             "kwargs": {"units": 4, "in_units": in_units, "seed": 1},
             "item_shape": [in_units], "max_batch_size": 8},
            canary_probes=4)
        print("rollout: v%d on %d replicas, canary error rate %s"
              % (report["version"], len(report["replicas"]),
                 report["canary"]["error_rate"]))
        time.sleep(phase_s)
    finally:
        stop.set()
        for t in threads:
            t.join(30)

    snap = fleet.router.snapshot()
    print("traffic: %d ok, %d failed; fleet p50/p95/p99 ms: %s / %s / %s"
          % (counts["ok"], counts["fail"],
             snap["latency"].get("p50_ms"), snap["latency"].get("p95_ms"),
             snap["latency"].get("p99_ms")))
    for rid, st in sorted(snap["replicas"].items()):
        c = st["counters"]
        print("  replica %s: %s, dispatched %d, retries %d, "
              "ejections %d, readmissions %d"
              % (rid, st["state"], c["dispatched"], c["retries"],
                 c["ejections"], c["readmissions"]))
    fleet.stop()
    if counts["fail"]:
        raise SystemExit("%d request(s) failed" % counts["fail"])
    print("fleet done")


if __name__ == "__main__":
    main()
