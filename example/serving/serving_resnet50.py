"""Serve ResNet-50 with mxnet_tpu.serving: model registry + dynamic
batcher + HTTP frontend, driven by concurrent HTTP clients.

What this demonstrates (the serving half of tests/test_serving.py, as a
runnable deployment shape):

1. load a hybridized model into the ``ModelRegistry`` — every batch
   bucket pre-compiles at load time, so no client pays a compile;
2. start the ``ModelServer`` HTTP frontend on an ephemeral port;
3. hammer it with concurrent ``ServingClient`` threads submitting small
   batches — the dynamic batcher coalesces them into bucket-padded XLA
   programs;
4. scrape the stats snapshot: batch occupancy + p50/p95/p99 queue-wait
   and end-to-end latency.

Run::

    python example/serving/serving_resnet50.py            # full: 224x224
    python example/serving/serving_resnet50.py --smoke    # CI: 64x64
"""
import argparse
import threading
import time

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import np as mxnp
from mxnet_tpu import serving


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="small inputs / few requests (CI lane)")
    ap.add_argument("--clients", type=int, default=None)
    ap.add_argument("--requests", type=int, default=None,
                    help="requests per client")
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--flush-ms", type=float, default=10.0)
    args = ap.parse_args()

    side = 64 if args.smoke else 224
    clients = args.clients or (2 if args.smoke else 8)
    requests = args.requests or (3 if args.smoke else 20)
    max_batch = args.max_batch or (4 if args.smoke else 16)
    item_shape = (3, side, side)

    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    mx.random.seed(0)
    net = resnet50_v1(classes=1000)
    net.initialize(mx.init.Xavier())
    net(mxnp.zeros((1,) + item_shape))  # finalize deferred shapes

    registry = serving.ModelRegistry()
    t0 = time.perf_counter()
    served = registry.load("resnet50", net, item_shape=item_shape,
                           max_batch_size=max_batch)
    print("loaded resnet50 v%d, %d buckets %s pre-compiled in %.1fs"
          % (served.version, len(served.buckets), served.buckets,
             time.perf_counter() - t0))

    with serving.ModelServer(registry, flush_ms=args.flush_ms,
                             max_queue_depth=8 * clients) as srv:
        host, port = srv.address
        print("serving on http://%s:%d  (try GET /v1/models, /v1/stats)"
              % (host, port))

        errors = []
        barrier = threading.Barrier(clients)

        def client_loop(cid):
            rng = onp.random.RandomState(cid)
            cli = serving.ServingClient(host, port, timeout=600)
            try:
                barrier.wait()
                for _ in range(requests):
                    x = rng.rand(1, *item_shape).astype("float32")
                    preds = cli.predict("resnet50", x)
                    assert preds.shape == (1, 1000)
            except Exception as e:  # pragma: no cover
                errors.append(repr(e))
            finally:
                cli.close()

        threads = [threading.Thread(target=client_loop, args=(c,))
                   for c in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        if errors:
            raise SystemExit("client errors: %s" % errors[:3])

        n = clients * requests
        print("%d requests from %d concurrent clients in %.2fs "
              "(%.1f img/s end-to-end over HTTP)" % (n, clients, dt, n / dt))

        stats = serving.ServingClient(host, port).stats()
        m = stats["models"]["resnet50"]
        print("batch occupancy: %s  (batches: %d for %d items)"
              % (m["batch_occupancy"], m["counters"]["batches_total"],
                 m["counters"]["items_total"]))
        print("queue wait  p50/p95/p99 ms: %s / %s / %s"
              % (m["queue_wait"].get("p50_ms"), m["queue_wait"].get("p95_ms"),
                 m["queue_wait"].get("p99_ms")))
        print("end-to-end  p50/p95/p99 ms: %s / %s / %s"
              % (m["total"].get("p50_ms"), m["total"].get("p95_ms"),
                 m["total"].get("p99_ms")))
        # graceful drain happens in ModelServer.stop() on context exit
    print("serving done")


if __name__ == "__main__":
    main()
