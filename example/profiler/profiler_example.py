#!/usr/bin/env python
"""Profiler usage (parity: reference example/profiler/profiler_executor.py
family): scoped host events + chrome-trace dump, with the XLA device
trace (xplane) enabled by config when a directory is given.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, profiler
from mxnet_tpu import np as mxnp
from mxnet_tpu.gluon import nn


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="profile.json")
    ap.add_argument("--xplane-dir", default=None,
                    help="also capture an XLA device trace here")
    args = ap.parse_args()

    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    cfg = {"filename": args.out}
    if args.xplane_dir:
        cfg["xplane_dir"] = args.xplane_dir
    profiler.set_config(**cfg)
    profiler.start()

    x = mxnp.random.uniform(size=(32, 20))
    y = mxnp.random.randint(0, 10, size=(32,))
    for step in range(5):
        with profiler.Task("train_step_%d" % step):
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(32)
    mx.waitall()
    profiler.stop()
    path = profiler.dump()
    print("chrome trace written to", path,
          "(open in chrome://tracing or perfetto)")


if __name__ == "__main__":
    main()
