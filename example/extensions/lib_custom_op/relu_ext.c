/* Example native extension (parity: reference
 * example/extensions/lib_custom_op/ — ABI-stable external ops loaded at
 * runtime, include/mxnet/lib_api.h).  Build:
 *     gcc -O2 -fPIC -shared -o librelu_ext.so relu_ext.c
 * Load:
 *     mx.library.load("librelu_ext.so")   → registers op "ext_relu6"
 */
#include <stdint.h>

#define EXPORT __attribute__((visibility("default")))

EXPORT int mxtpu_ext_num_ops(void) { return 1; }

EXPORT const char* mxtpu_ext_op_name(int i) {
  (void)i;
  return "ext_relu6";
}

EXPORT void mxtpu_ext_op_compute(int i, const float* in, float* out,
                                 int64_t n) {
  (void)i;
  for (int64_t k = 0; k < n; ++k) {
    float v = in[k];
    out[k] = v < 0.f ? 0.f : (v > 6.f ? 6.f : v);
  }
}

EXPORT void mxtpu_ext_op_grad(int i, const float* in, const float* gout,
                              float* gin, int64_t n) {
  (void)i;
  for (int64_t k = 0; k < n; ++k) {
    gin[k] = (in[k] > 0.f && in[k] < 6.f) ? gout[k] : 0.f;
  }
}
