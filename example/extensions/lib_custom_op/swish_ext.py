"""Example Python extension (parity: reference example/extensions/
lib_custom_op/ custom ops defined in Python).  Load with
mx.library.load(".../swish_ext.py") — registers op "ext_swish"."""
import numpy as onp


def register_ops(mx):
    @mx.operator.register("ext_swish")
    class SwishProp(mx.operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=True)

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, in_shapes, in_dtypes):
            return Swish()

    class Swish(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            x = in_data[0].asnumpy()
            sig = 1.0 / (1.0 + onp.exp(-x))
            self.assign(out_data[0], req[0], x * sig)

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            x = in_data[0].asnumpy()
            g = out_grad[0].asnumpy()
            sig = 1.0 / (1.0 + onp.exp(-x))
            self.assign(in_grad[0], req[0],
                        g * (sig + x * sig * (1 - sig)))
