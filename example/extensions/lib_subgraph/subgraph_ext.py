"""Example partitioner extension (parity: reference
example/extensions/lib_subgraph — a CustomPartitioner loaded from an
external library via REGISTER_PARTITIONER, include/mxnet/lib_api.h:837,
:940).

Load with mx.library.load(".../subgraph_ext.py") — registers subgraph
property "DENSE_FUSE": groups FullyConnected/Dense + elementwise
activations into subgraph nodes (the conv/FC+eltwise fusion pattern the
reference's ONEDNN subgraph backend targets).
"""


def register_partitioners(mx):
    sg = mx.subgraph

    FUSABLE = {"legacy:FullyConnected", "npx:fully_connected",
               "npx:relu", "np:tanh", "npx:activation",
               "legacy:Activation", "npx:sigmoid"}

    @sg.register_property("DENSE_FUSE")
    class DenseFuseProperty(sg.SubgraphProperty):
        def create_selector(self):
            return sg.OpNameSelector(FUSABLE)
