/* Example NATIVE graph-pass extension (parity: reference
 * example/extensions/lib_pass/pass_lib.cc — a CustomPass compiled into
 * an external .so and loaded at runtime, lib_api.h:806).
 *
 * ABI (see mxnet_tpu/library.py): a pass receives the graph's JSON
 * serialization and returns a malloc'd transformed JSON string.
 *
 * "relu-to-tanh-native" rewrites op ids "npx:relu" -> "np:tanh" by
 * substring substitution over the serialized op fields — the same toy
 * transform the reference example performs with its JsonParser.
 *
 * Build: gcc -shared -fPIC -o libpass_ext.so pass_lib.c
 */
#include <stdlib.h>
#include <string.h>

#ifdef __cplusplus
extern "C" {
#endif

static const char* PASS_NAMES[] = {"relu-to-tanh-native"};

int mxtpu_ext_num_passes(void) { return 1; }

const char* mxtpu_ext_pass_name(int i) { return PASS_NAMES[i]; }

char* mxtpu_ext_pass_apply(int i, const char* graph_json) {
  (void)i;
  const char* from = "\"npx:relu\"";
  const char* to = "\"np:tanh\"";
  size_t flen = strlen(from), tlen = strlen(to);
  size_t n = strlen(graph_json);
  /* worst case: every byte starts a match (tlen <= flen here anyway) */
  char* out = (char*)malloc(n * (tlen > flen ? tlen : flen) / flen + tlen + 1);
  if (!out) return NULL;
  const char* src = graph_json;
  char* dst = out;
  while (*src) {
    if (strncmp(src, from, flen) == 0) {
      memcpy(dst, to, tlen);
      dst += tlen;
      src += flen;
    } else {
      *dst++ = *src++;
    }
  }
  *dst = '\0';
  return out;
}

void mxtpu_ext_free(char* p) { free(p); }

#ifdef __cplusplus
}
#endif
