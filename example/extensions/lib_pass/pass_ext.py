"""Example graph-pass extension (parity: reference
example/extensions/lib_pass — a custom pass loaded from an external
library via REGISTER_PASS, include/mxnet/lib_api.h:806,:936).

Load with mx.library.load(".../pass_ext.py") — registers:

  * "drop-dropout":   removes npx:dropout nodes (inference cleanup)
  * "tanh-to-relu":   swaps np:tanh activations for npx:relu
"""


def register_passes(mx):
    gp = mx.graph_pass

    @gp.register("drop-dropout")
    def drop_dropout(sym):
        def fn(node, new_inputs):
            if node._kind == "op" and node._op in ("npx:dropout",
                                                   "legacy:Dropout"):
                return new_inputs[0]
            return None
        return gp.rewrite(sym, fn)

    @gp.register("tanh-to-relu")
    def tanh_to_relu(sym):
        from mxnet_tpu.sym_api import Symbol

        def fn(node, new_inputs):
            if node._kind == "op" and node._op == "np:tanh":
                return Symbol("op", name=node.name, op="npx:relu",
                              inputs=new_inputs, attrs=dict(node._attrs))
            return None
        return gp.rewrite(sym, fn)
