/* Multi-threaded inference through the C API (parity target: reference
 * example/multi_threaded_inference — concurrent inference on one shared
 * thread-safe CachedOp).
 *
 * N pthreads share ONE CachedOp handle and invoke it concurrently; each
 * entry point acquires the embedded interpreter's GIL internally, so the
 * embedder needs no locking of its own.  Exit code 0 iff every thread's
 * result matches the single-threaded reference.
 *
 * Build/run (driven by tests/test_c_train.py::test_c_multi_threaded_inference):
 *   gcc mti.c -I include -L mxnet_tpu/lib -lmxtpu_capi -lpthread \
 *       -Wl,-rpath,mxnet_tpu/lib -o mti && ./mti graph.json
 */
#include <math.h>
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>

#include "mxtpu_c_api.h"

#define N_THREADS 4
#define N_ITERS 8
#define DIM 16

static MXTHandle g_op;
static float g_ref[DIM];
static int g_fail = 0;

static void* worker(void* arg) {
  long tid = (long)arg;
  int it, i;
  for (it = 0; it < N_ITERS; ++it) {
    int64_t shape[] = {1, DIM};
    float buf[DIM];
    for (i = 0; i < DIM; ++i) buf[i] = (float)i / DIM;
    MXTHandle x, outs[2];
    int nout = 2;
    if (MXTNDArrayFromBytes(shape, 2, "float32", buf, sizeof(buf), &x) ||
        MXTCachedOpInvoke(g_op, &x, 1, outs, &nout) ||
        MXTNDArraySyncCopyToCPU(outs[0], buf, sizeof(buf))) {
      fprintf(stderr, "thread %ld: %s\n", tid, MXTGetLastError());
      g_fail = 1;
      return NULL;
    }
    for (i = 0; i < DIM; ++i) {
      if (fabsf(buf[i] - g_ref[i]) > 1e-5f) {
        fprintf(stderr, "thread %ld: mismatch at %d (%f vs %f)\n",
                tid, i, buf[i], g_ref[i]);
        g_fail = 1;
      }
    }
    MXTNDArrayFree(x);
    MXTNDArrayFree(outs[0]);
  }
  return NULL;
}

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: mti <sym-graph.json>\n");
    return 2;
  }
  FILE* f = fopen(argv[1], "rb");
  if (!f) { perror("open"); return 2; }
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  char* json = (char*)malloc(sz + 1);
  if (fread(json, 1, sz, f) != (size_t)sz) { fclose(f); return 2; }
  json[sz] = 0;
  fclose(f);

  if (MXTCachedOpCreate(json, &g_op)) {
    fprintf(stderr, "create: %s\n", MXTGetLastError());
    return 1;
  }
  free(json);

  /* single-threaded reference result */
  {
    int64_t shape[] = {1, DIM};
    float buf[DIM];
    int i, nout = 2;
    MXTHandle x, outs[2];
    for (i = 0; i < DIM; ++i) buf[i] = (float)i / DIM;
    if (MXTNDArrayFromBytes(shape, 2, "float32", buf, sizeof(buf), &x) ||
        MXTCachedOpInvoke(g_op, &x, 1, outs, &nout) ||
        MXTNDArraySyncCopyToCPU(outs[0], g_ref, sizeof(g_ref))) {
      fprintf(stderr, "ref: %s\n", MXTGetLastError());
      return 1;
    }
    MXTNDArrayFree(x);
    MXTNDArrayFree(outs[0]);
  }

  pthread_t th[N_THREADS];
  long t;
  for (t = 0; t < N_THREADS; ++t)
    pthread_create(&th[t], NULL, worker, (void*)t);
  for (t = 0; t < N_THREADS; ++t)
    pthread_join(th[t], NULL);
  MXTCachedOpFree(g_op);
  if (g_fail) return 1;
  printf("OK: %d threads x %d invokes matched the reference\n",
         N_THREADS, N_ITERS);
  return 0;
}
