/* Minimal embedder using the C predict API (parity: reference
 * example/image-classification/predict-cpp over c_predict_api.h).
 * Usage: predict_example <symbol.json> <params.npz> <n_in> <v0> <v1> ...
 * Prints the flat output values. */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

extern void* MXTPredCreate(const char*, const char*, const char*);
extern const char* MXTPredLastError(void*);
extern int MXTPredSetInput(void*, const char*, const float*,
                           const int64_t*, int);
extern int MXTPredForward(void*);
extern int MXTPredGetOutputShape(void*, int64_t*, int*, int);
extern int MXTPredGetOutput(void*, float*, int64_t);
extern void MXTPredFree(void*);

int main(int argc, char** argv) {
  if (argc < 4) {
    fprintf(stderr, "usage: %s sym params n_in v...\n", argv[0]);
    return 2;
  }
  void* h = MXTPredCreate(argv[1], argv[2], "data");
  if (h == NULL) {
    fprintf(stderr, "create failed\n");
    return 1;
  }
  int n = atoi(argv[3]);
  float* in = (float*)malloc(sizeof(float) * n);
  for (int i = 0; i < n && 4 + i < argc; ++i) in[i] = atof(argv[4 + i]);
  int64_t shape[2] = {1, n};
  if (MXTPredSetInput(h, "data", in, shape, 2) != 0 ||
      MXTPredForward(h) != 0) {
    fprintf(stderr, "predict failed: %s\n", MXTPredLastError(h));
    return 1;
  }
  int64_t oshape[8];
  int ndim = 0;
  if (MXTPredGetOutputShape(h, oshape, &ndim, 8) != 0) return 1;
  int64_t total = 1;
  for (int i = 0; i < ndim; ++i) total *= oshape[i];
  float* out = (float*)malloc(sizeof(float) * total);
  int got = MXTPredGetOutput(h, out, total);
  if (got < 0) return 1;
  for (int i = 0; i < got; ++i) printf("%.6f\n", out[i]);
  MXTPredFree(h);
  free(in);
  free(out);
  return 0;
}
