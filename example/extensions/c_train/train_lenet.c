/* Train LeNet end-to-end from C through libmxtpu_capi.so.
 *
 * Parity: the reference's C-API training loop (what a non-Python
 * embedder writes against include/mxnet/c_api.h): create parameter
 * NDArrays, run imperative forward ops under autograd recording,
 * backward, then SGD updates via the optimizer handle.  Prints the loss
 * per iteration; exits 0 iff the loss decreased.
 *
 * Build/run (the test driver tests/test_c_train.py does this):
 *   gcc train_lenet.c -I include -L mxnet_tpu/lib -lmxtpu_capi \
 *       -Wl,-rpath,mxnet_tpu/lib -o train_lenet && ./train_lenet
 */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>

#include "mxtpu_c_api.h"

#define CHECK(expr)                                                    \
  do {                                                                 \
    if ((expr) != 0) {                                                 \
      fprintf(stderr, "FAIL %s:%d %s: %s\n", __FILE__, __LINE__, #expr, \
              MXTGetLastError());                                      \
      exit(1);                                                         \
    }                                                                  \
  } while (0)

static MXTHandle randn(int64_t* shape, int ndim, double scale) {
  /* host-side gaussian-ish init: sum of 4 uniforms, centered */
  size_t n = 1;
  int i;
  for (i = 0; i < ndim; ++i) n *= (size_t)shape[i];
  float* buf = (float*)malloc(n * sizeof(float));
  size_t j;
  for (j = 0; j < n; ++j) {
    double s = 0;
    for (i = 0; i < 4; ++i) s += (double)rand() / RAND_MAX;
    buf[j] = (float)((s - 2.0) * scale);
  }
  MXTHandle h;
  CHECK(MXTNDArrayFromBytes(shape, ndim, "float32", buf,
                            n * sizeof(float), &h));
  free(buf);
  return h;
}

/* one imperative op with one output */
static MXTHandle op1(const char* name, MXTHandle* ins, int nin,
                     const char* kwargs) {
  MXTHandle outs[4];
  int nout = 4;
  CHECK(MXTImperativeInvoke(name, ins, nin, kwargs, outs, &nout));
  if (nout < 1) {
    fprintf(stderr, "op %s returned no outputs\n", name);
    exit(1);
  }
  /* extra outputs (e.g. none expected here) are released */
  int i;
  for (i = 1; i < nout; ++i) MXTNDArrayFree(outs[i]);
  return outs[0];
}

int main(void) {
  srand(7);
  CHECK(MXTRandomSeed(7));

  int ver;
  CHECK(MXTVersion(&ver));
  fprintf(stderr, "mxtpu c api version %d\n", ver);

  const int B = 32, CLASSES = 10;

  /* LeNet parameters */
  int64_t s_c1w[] = {6, 1, 5, 5}, s_c1b[] = {6};
  int64_t s_c2w[] = {16, 6, 5, 5}, s_c2b[] = {16};
  int64_t s_f1w[] = {120, 400}, s_f1b[] = {120};
  int64_t s_f2w[] = {84, 120}, s_f2b[] = {84};
  int64_t s_f3w[] = {10, 84}, s_f3b[] = {10};
  MXTHandle params[10];
  params[0] = randn(s_c1w, 4, 0.2);
  params[1] = randn(s_c1b, 1, 0.0);
  params[2] = randn(s_c2w, 4, 0.1);
  params[3] = randn(s_c2b, 1, 0.0);
  params[4] = randn(s_f1w, 2, 0.1);
  params[5] = randn(s_f1b, 1, 0.0);
  params[6] = randn(s_f2w, 2, 0.1);
  params[7] = randn(s_f2b, 1, 0.0);
  params[8] = randn(s_f3w, 2, 0.1);
  params[9] = randn(s_f3b, 1, 0.0);
  CHECK(MXTAutogradMarkVariables(10, params));

  /* synthetic batch: images + labels (labels = argmax of a fixed random
   * projection, so the task is learnable) */
  int64_t s_x[] = {B, 1, 28, 28};
  MXTHandle x = randn(s_x, 4, 0.5);
  float labels[32];
  int i;
  for (i = 0; i < B; ++i) labels[i] = (float)(i % CLASSES);
  int64_t s_y[] = {B};
  MXTHandle y;
  CHECK(MXTNDArrayFromBytes(s_y, 1, "float32", labels, sizeof(labels), &y));

  MXTHandle opt;
  CHECK(MXTOptimizerCreate(
      "sgd", "{\"learning_rate\": 0.1, \"momentum\": 0.9}", &opt));

  double first = 0, last = 0;
  int it;
  for (it = 0; it < 30; ++it) {
    int prev;
    CHECK(MXTAutogradSetRecording(1, &prev));
    CHECK(MXTAutogradSetTraining(1, NULL));

    /* forward: conv-tanh-pool x2 -> dense x3 */
    MXTHandle c1_in[] = {x, params[0], params[1]};
    MXTHandle h = op1("convolution", c1_in, 3,
                      "{\"kernel\": [5, 5], \"num_filter\": 6,"
                      " \"pad\": [2, 2]}");
    MXTHandle t = op1("tanh", &h, 1, "");
    MXTNDArrayFree(h);
    h = op1("pooling", &t, 1, "{\"kernel\": [2, 2], \"stride\": [2, 2]}");
    MXTNDArrayFree(t);

    MXTHandle c2_in[] = {h, params[2], params[3]};
    t = op1("convolution", c2_in, 3,
            "{\"kernel\": [5, 5], \"num_filter\": 16}");
    MXTNDArrayFree(h);
    h = op1("tanh", &t, 1, "");
    MXTNDArrayFree(t);
    t = op1("pooling", &h, 1, "{\"kernel\": [2, 2], \"stride\": [2, 2]}");
    MXTNDArrayFree(h);

    MXTHandle f1_in[] = {t, params[4], params[5]};
    h = op1("fully_connected", f1_in, 3, "{\"num_hidden\": 120}");
    MXTNDArrayFree(t);
    t = op1("tanh", &h, 1, "");
    MXTNDArrayFree(h);
    MXTHandle f2_in[] = {t, params[6], params[7]};
    h = op1("fully_connected", f2_in, 3, "{\"num_hidden\": 84}");
    MXTNDArrayFree(t);
    t = op1("tanh", &h, 1, "");
    MXTNDArrayFree(h);
    MXTHandle f3_in[] = {t, params[8], params[9]};
    MXTHandle logits = op1("fully_connected", f3_in, 3,
                           "{\"num_hidden\": 10}");
    MXTNDArrayFree(t);

    /* softmax cross-entropy: -mean(pick(log_softmax(logits), y)) */
    h = op1("log_softmax", &logits, 1, "{\"axis\": -1}");
    MXTNDArrayFree(logits);
    MXTHandle pick_in[] = {h, y};
    t = op1("pick", pick_in, 2, "{\"axis\": -1}");
    MXTNDArrayFree(h);
    h = op1("mean", &t, 1, "");
    MXTNDArrayFree(t);
    MXTHandle loss = op1("negative", &h, 1, "");
    MXTNDArrayFree(h);

    CHECK(MXTAutogradSetRecording(0, &prev));
    CHECK(MXTAutogradBackward(1, &loss, 0));

    /* SGD step on every parameter */
    for (i = 0; i < 10; ++i) {
      MXTHandle g;
      CHECK(MXTNDArrayGetGrad(params[i], &g));
      CHECK(MXTOptimizerUpdate(opt, i, params[i], g));
      MXTNDArrayFree(g);
    }

    float lv;
    CHECK(MXTNDArraySyncCopyToCPU(loss, &lv, sizeof(lv)));
    MXTNDArrayFree(loss);
    if (it == 0) first = lv;
    last = lv;
    printf("iter %d loss %.4f\n", it, lv);
  }

  CHECK(MXTNDArrayWaitAll());
  MXTOptimizerFree(opt);
  MXTNDArrayFree(x);
  MXTNDArrayFree(y);
  for (i = 0; i < 10; ++i) MXTNDArrayFree(params[i]);

  if (!(last < first * 0.5) || !isfinite(last)) {
    fprintf(stderr, "loss did not decrease: %.4f -> %.4f\n", first, last);
    return 1;
  }
  fprintf(stderr, "OK: loss %.4f -> %.4f\n", first, last);
  return 0;
}
