#!/usr/bin/env python
"""Operator micro-benchmark harness.

Parity: reference `benchmark/opperf/opperf.py` — per-operator fwd/bwd
latency across the registered op surface, used as the perf-regression
harness (SURVEY.md §4/§6).

Usage:
  python benchmark/opperf.py                  # standard op set
  python benchmark/opperf.py --ops add,dot    # subset
  python benchmark/opperf.py --json out.json  # machine-readable dump
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu import np as mxnp
from mxnet_tpu import npx


def _u(shape):
    return mxnp.random.uniform(size=shape)


# (name, forward_closure_factory, differentiable_inputs_factory)
def _registry(large):
    n = 1024 if large else 256
    c = 64 if large else 16
    img = (32, c, 28, 28) if large else (8, c, 14, 14)
    OPS = {
        # elemwise / broadcast
        "add": lambda: (lambda a, b: a + b, [_u((n, n)), _u((n, n))]),
        "multiply": lambda: (lambda a, b: a * b, [_u((n, n)), _u((n, n))]),
        "exp": lambda: (mxnp.exp, [_u((n, n))]),
        "tanh": lambda: (mxnp.tanh, [_u((n, n))]),
        # reductions
        "sum": lambda: (lambda a: a.sum(), [_u((n, n))]),
        "mean_axis": lambda: (lambda a: a.mean(axis=1), [_u((n, n))]),
        # matmul family
        "dot": lambda: (mxnp.dot, [_u((n, n)), _u((n, n))]),
        "batch_dot": lambda: (npx.batch_dot, [_u((16, n // 4, n // 4)),
                                              _u((16, n // 4, n // 4))]),
        "einsum_bij_bjk": lambda: (
            lambda a, b: mxnp.einsum("bij,bjk->bik", a, b),
            [_u((16, n // 4, n // 4)), _u((16, n // 4, n // 4))]),
        # nn
        "fully_connected": lambda: (
            lambda x, w, b: npx.fully_connected(x, w, b, num_hidden=n),
            [_u((128, n)), _u((n, n)), _u((n,))]),
        "convolution": lambda: (
            lambda x, w: npx.convolution(x, w, kernel=(3, 3), pad=(1, 1),
                                         num_filter=c, no_bias=True),
            [_u(img), _u((c, c, 3, 3))]),
        "pooling": lambda: (
            lambda x: npx.pooling(x, kernel=(2, 2), stride=(2, 2)),
            [_u(img)]),
        "softmax": lambda: (npx.softmax, [_u((n, n))]),
        "layer_norm": lambda: (
            lambda x, g, b: npx.layer_norm(x, g, b),
            [_u((n, n)), _u((n,)), _u((n,))]),
        "batch_norm_inf": lambda: (
            lambda x, g, b, m, v: npx.batch_norm(x, g, b, m, v,
                                                 use_global_stats=True),
            [_u(img), _u((c,)), _u((c,)), _u((c,)), _u((c,))]),
        # indexing / shapes
        "transpose": lambda: (lambda a: a.transpose(), [_u((n, n))]),
        "take": lambda: (
            lambda a: a.take(mxnp.array(onp.arange(64)), axis=0),
            [_u((n, n))]),
        "concat": lambda: (
            lambda a, b: mxnp.concatenate([a, b], axis=1),
            [_u((n, n)), _u((n, n))]),
        # attention
        "flash_attention": lambda: (
            npx.flash_attention,
            [_u((4, 8, 128, 64)), _u((4, 8, 128, 64)),
             _u((4, 8, 128, 64))]),
    }
    return OPS


def bench_op(make, warmup=3, iters=20, backward=True):
    from mxnet_tpu import engine
    fn, inputs = make()
    for x in inputs:
        x.attach_grad()
    # forward timing: bulk size 1 = true per-op dispatch (each op is its
    # own cached executable, dispatched async; one sync per window)
    with engine.bulk(1):
        for _ in range(warmup):
            out = fn(*inputs)
        out.wait_to_read()
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*inputs)
        out.wait_to_read()
        fwd_ms = (time.perf_counter() - t0) / iters * 1e3

    bwd_ms = None
    if backward:
        def run_bwd():
            with autograd.record():
                o = fn(*inputs)
                loss = o.sum() if hasattr(o, "sum") else o
            loss.backward()
        try:
            for _ in range(warmup):
                run_bwd()
            inputs[0].grad.wait_to_read()
            t0 = time.perf_counter()
            for _ in range(iters):
                run_bwd()
            # one sync per window (same discipline as the fwd loop): the
            # steady-state cost of an eager fwd+bwd is the async dispatch,
            # not a host round-trip per op
            inputs[0].grad.wait_to_read()
            bwd_ms = (time.perf_counter() - t0) / iters * 1e3
        except Exception:
            bwd_ms = None
    return fwd_ms, bwd_ms


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", default=None, help="comma-separated subset")
    ap.add_argument("--large", action="store_true")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    registry = _registry(args.large)
    names = args.ops.split(",") if args.ops else list(registry)
    rows = []
    print("%-20s %12s %12s" % ("op", "fwd (ms)", "fwd+bwd (ms)"))
    print("-" * 48)
    for name in names:
        if name not in registry:
            print("%-20s %12s" % (name, "unknown"))
            continue
        fwd, bwd = bench_op(registry[name], iters=args.iters)
        rows.append({"op": name, "fwd_ms": round(fwd, 4),
                     "fwd_bwd_ms": round(bwd, 4) if bwd else None})
        print("%-20s %12.4f %12s" % (
            name, fwd, "%.4f" % bwd if bwd else "n/a"))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rows, f, indent=1)
        print("wrote", args.json)


if __name__ == "__main__":
    main()
