#!/usr/bin/env python
"""Operator micro-benchmark harness — FULL registered-op surface.

Parity: reference `benchmark/opperf/opperf.py`, which enumerates every
registered operator, auto-generates inputs, and records fwd / fwd+bwd
latencies as the perf-regression surface (SURVEY.md §4/§6).

This harness walks the live op namespaces (mx.np, mx.npx, np.linalg,
np.random, contrib.ops), synthesizes arguments per op (generic probing +
an override table for shape/axis/index-taking ops), and times each op's
eager dispatch:

  fwd:      async dispatches, one sync per window (steady-state eager
            cost; a sync per op would measure the host-fetch RTT)
  fwd+bwd:  autograd.record + backward per iteration, same discipline

Medians are taken across windows (robust against tunnel interference on
the shared bench chip).

Usage:
  python benchmark/opperf.py                    # full surface
  python benchmark/opperf.py --ops np:add,npx:softmax
  python benchmark/opperf.py --json OPPERF.json
  python benchmark/opperf.py --probe-only       # coverage report only
"""
from __future__ import annotations

import argparse
import inspect
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

import jax

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu import np as mxnp
from mxnet_tpu import npx
from mxnet_tpu.ndarray import ndarray


# ---------------------------------------------------------------------------
# argument synthesis
# ---------------------------------------------------------------------------
N = 256          # square matrix edge
V = 4096         # vector length
IMG = (8, 16, 28, 28)


def _u(shape, dtype="float32"):
    a = mxnp.random.uniform(low=0.1, high=1.0, size=shape)
    return a.astype(dtype) if dtype != "float32" else a


def _idx(n, hi):
    return mxnp.array(onp.random.RandomState(0).randint(0, hi, size=n))


def _spd():
    m = onp.random.RandomState(0).randn(32, 32).astype("float32")
    return mxnp.array(m @ m.T + 32 * onp.eye(32, dtype="float32"))


# ops that are not benchable ops (array constructors from python data,
# introspection, host-sync utilities, aliases of the ndarray class, ...)
EXCLUDE = {
    "np": {"array", "asarray", "ascontiguousarray", "asnumpy", "apply_op",
           "astype", "copyto", "dtype", "empty", "empty_like", "finfo",
           "iinfo", "from_numpy", "frombuffer", "fromfunction", "get_include",
           "issubdtype", "may_share_memory", "shares_memory", "ndarray",
           "newaxis", "result_type", "promote_types", "save", "savez",
           "load", "seterr", "set_printoptions", "shape", "size", "ndim",
           "broadcast_shapes", "can_cast", "min_scalar_type", "isscalar",
           "iterable", "printoptions", "typename", "waitall", "abs_",
           "bool", "bool_", "set_module"},
    "npx": {"set_np", "reset_np", "use_np", "use_np_shape", "use_np_array",
            "is_np_array", "is_np_shape", "np_shape", "np_array", "npx",
            "waitall", "load", "save", "savez", "seed", "current_device",
            "num_gpus", "gpu", "gpu_memory_info", "cpu", "cpu_pinned"},
    "linalg": set(),
    "random": {"seed", "default_rng", "get_state", "set_state"},
    "contrib": set(),
}

# per-op argument overrides: name -> (args_thunk, needs_grad) | None to
# skip with a documented reason (thunks make fresh buffers per run)
OVERRIDES = {
    # creation / shape-taking
    "np:zeros": (lambda: (((N, N),), {}), False),
    "np:ones": (lambda: (((N, N),), {}), False),
    "np:full": (lambda: (((N, N), 3.14), {}), False),
    "np:eye": (lambda: ((N,), {}), False),
    "np:identity": (lambda: ((N,), {}), False),
    "np:arange": (lambda: ((V,), {}), False),
    "np:linspace": (lambda: ((0.0, 1.0, V), {}), False),
    "np:logspace": (lambda: ((0.0, 3.0, V), {}), False),
    "np:tri": (lambda: ((N,), {}), False),
    "np:indices": (lambda: (((32, 32),), {}), False),
    "np:bartlett": (lambda: ((V,), {}), False),
    "np:blackman": (lambda: ((V,), {}), False),
    "np:hamming": (lambda: ((V,), {}), False),
    "np:hanning": (lambda: ((V,), {}), False),
    "np:kaiser": (lambda: ((V, 14.0), {}), False),
    # reshape / movement
    "np:reshape": (lambda: ((_u((N, N)), (N * N,)), {}), True),
    "np:swapaxes": (lambda: ((_u((8, 16, 32)), 0, 2), {}), True),
    "np:moveaxis": (lambda: ((_u((8, 16, 32)), 0, 2), {}), True),
    "np:rollaxis": (lambda: ((_u((8, 16, 32)), 2), {}), True),
    "np:expand_dims": (lambda: ((_u((N, N)), 0), {}), True),
    "np:squeeze": (lambda: ((_u((1, N, N)),), {}), True),
    "np:rot90": (lambda: ((_u((N, N)),), {}), True),
    "np:roll": (lambda: ((_u((N, N)), 3), {}), True),
    "np:tile": (lambda: ((_u((64, 64)), (2, 2)), {}), True),
    "np:repeat": (lambda: ((_u((N, N)), 2), {}), True),
    "np:broadcast_to": (lambda: ((_u((1, N)), (N, N)), {}), True),
    "np:broadcast_arrays": (lambda: (([_u((1, N)), _u((N, 1))],), {}),
                            False),
    # joining / splitting
    "np:concatenate": (lambda: (([_u((N, N)), _u((N, N))],), {}), True),
    "np:stack": (lambda: (([_u((N, N)), _u((N, N))],), {}), True),
    "np:vstack": (lambda: (([_u((N, N)), _u((N, N))],), {}), True),
    "np:hstack": (lambda: (([_u((N, N)), _u((N, N))],), {}), True),
    "np:dstack": (lambda: (([_u((N, N)), _u((N, N))],), {}), True),
    "np:column_stack": (lambda: (([_u((N,)), _u((N,))],), {}), True),
    "np:row_stack": (lambda: (([_u((N, N)), _u((N, N))],), {}), True),
    "np:split": (lambda: ((_u((N, N)), 4), {}), False),
    "np:array_split": (lambda: ((_u((N, N)), 4), {}), False),
    "np:hsplit": (lambda: ((_u((N, N)), 4), {}), False),
    "np:vsplit": (lambda: ((_u((N, N)), 4), {}), False),
    "np:dsplit": (lambda: ((_u((8, 8, 8)), 4), {}), False),
    "np:append": (lambda: ((_u((N, N)), _u((N, N))), {}), True),
    "np:insert": (lambda: ((_u((V,)), 5, 1.0), {}), False),
    "np:delete": (lambda: ((_u((V,)), 5), {}), False),
    "np:pad": (lambda: ((_u((N, N)), 2), {}), True),
    # indexing
    "np:take": (lambda: ((_u((N, N)), _idx(64, N)), {"axis": 0}), True),
    "np:take_along_axis": (
        lambda: ((_u((N, N)), _idx(N, N).reshape(1, N).astype("int64")),
                 {}), False),
    "np:put_along_axis": None,  # in-place host semantics
    "np:choose": None,
    "np:compress": (lambda: ((mxnp.array([True] * 32), _u((N, N))),
                             {"axis": 0}), False),
    "np:extract": (lambda: ((_u((N, N)) > 0.5, _u((N, N))), {}), False),
    "np:where": (lambda: ((_u((N, N)) > 0.5, _u((N, N)), _u((N, N))),
                          {}), True),
    "np:select": (lambda: (([_u((V,)) > 0.5], [_u((V,))]), {}), False),
    "np:searchsorted": (lambda: ((mxnp.sort(_u((V,))), _u((64,))), {}),
                        False),
    "np:bincount": (lambda: ((_idx(V, 64).astype("int32"),), {}), False),
    "np:digitize": (lambda: ((_u((V,)), mxnp.sort(_u((16,)))), {}), False),
    "np:unravel_index": (lambda: ((_idx(64, N * N), (N, N)), {}), False),
    "np:ravel_multi_index": (
        lambda: (((_idx(64, N), _idx(64, N)), (N, N)), {}), False),
    "np:diag": (lambda: ((_u((N, N)),), {}), True),
    "np:diagonal": (lambda: ((_u((N, N)),), {}), True),
    "np:diagflat": (lambda: ((_u((64,)),), {}), True),
    "np:diag_indices_from": (lambda: ((_u((N, N)),), {}), False),
    "np:tril": (lambda: ((_u((N, N)),), {}), True),
    "np:triu": (lambda: ((_u((N, N)),), {}), True),
    "np:tril_indices": (lambda: ((64,), {}), False),
    "np:trace": (lambda: ((_u((N, N)),), {}), True),
    "np:nonzero": (lambda: ((_u((N, N)) > 0.5,), {}), False),
    "np:flatnonzero": (lambda: ((_u((V,)) > 0.5,), {}), False),
    "np:argwhere": (lambda: ((_u((N, N)) > 0.5,), {}), False),
    "np:count_nonzero": (lambda: ((_u((N, N)) > 0.5,), {}), False),
    "np:unique": (lambda: ((_idx(V, 64),), {}), False),
    "np:isin": (lambda: ((_idx(V, 64), _idx(16, 64)), {}), False),
    "np:in1d": (lambda: ((_idx(V, 64), _idx(16, 64)), {}), False),
    "np:intersect1d": (lambda: ((_idx(V, 64), _idx(V, 64)), {}), False),
    "np:union1d": (lambda: ((_idx(V, 64), _idx(V, 64)), {}), False),
    "np:setdiff1d": (lambda: ((_idx(V, 64), _idx(16, 64)), {}), False),
    "np:setxor1d": (lambda: ((_idx(V, 64), _idx(V, 64)), {}), False),
    "np:trim_zeros": (lambda: ((mxnp.array([0.0, 1, 2, 0]),), {}), False),
    # matmul family
    "np:dot": (lambda: ((_u((N, N)), _u((N, N))), {}), True),
    "np:matmul": (lambda: ((_u((N, N)), _u((N, N))), {}), True),
    "np:inner": (lambda: ((_u((N, N)), _u((N, N))), {}), True),
    "np:outer": (lambda: ((_u((V,)), _u((V,))), {}), True),
    "np:vdot": (lambda: ((_u((V,)), _u((V,))), {}), True),
    "np:cross": (lambda: ((_u((V, 3)), _u((V, 3))), {}), True),
    "np:kron": (lambda: ((_u((16, 16)), _u((16, 16))), {}), True),
    "np:tensordot": (lambda: ((_u((N, N)), _u((N, N))), {}), True),
    "np:einsum": (lambda: (("ij,jk->ik", _u((N, N)), _u((N, N))), {}),
                  False),
    # reductions / stats needing special args
    "np:percentile": (lambda: ((_u((N, N)), 50.0), {}), False),
    "np:quantile": (lambda: ((_u((N, N)), 0.5), {}), False),
    "np:nanpercentile": (lambda: ((_u((N, N)), 50.0), {}), False),
    "np:nanquantile": (lambda: ((_u((N, N)), 0.5), {}), False),
    "np:histogram": (lambda: ((_u((V,)),), {}), False),
    "np:correlate": (lambda: ((_u((V,)), _u((64,))), {}), False),
    "np:convolve": (lambda: ((_u((V,)), _u((64,))), {}), False),
    "np:cov": (lambda: ((_u((16, V)),), {}), False),
    "np:corrcoef": (lambda: ((_u((16, V)),), {}), False),
    "np:gradient": (lambda: ((_u((V,)),), {}), False),
    "np:diff": (lambda: ((_u((N, N)),), {}), True),
    "np:ediff1d": (lambda: ((_u((V,)),), {}), True),
    "np:trapz": (lambda: ((_u((V,)),), {}), False),
    "np:interp": (lambda: ((_u((V,)), mxnp.sort(_u((64,))), _u((64,))),
                           {}), False),
    "np:meshgrid": (lambda: ((_u((64,)), _u((64,))), {}), False),
    # int / bool semantics
    "np:left_shift": (lambda: ((_idx(V, 8).astype("int32"), 2), {}), False),
    "np:right_shift": (lambda: ((_idx(V, 8).astype("int32"), 2), {}),
                       False),
    "np:bitwise_and": (lambda: ((_idx(V, 64).astype("int32"),
                                 _idx(V, 64).astype("int32")), {}), False),
    "np:bitwise_or": (lambda: ((_idx(V, 64).astype("int32"),
                                _idx(V, 64).astype("int32")), {}), False),
    "np:bitwise_xor": (lambda: ((_idx(V, 64).astype("int32"),
                                 _idx(V, 64).astype("int32")), {}), False),
    "np:bitwise_not": (lambda: ((_idx(V, 64).astype("int32"),), {}), False),
    "np:invert": (lambda: ((_idx(V, 64).astype("int32"),), {}), False),
    "np:logical_and": (lambda: ((_u((N, N)) > 0.5, _u((N, N)) > 0.5), {}),
                       False),
    "np:logical_or": (lambda: ((_u((N, N)) > 0.5, _u((N, N)) > 0.5), {}),
                      False),
    "np:logical_xor": (lambda: ((_u((N, N)) > 0.5, _u((N, N)) > 0.5), {}),
                       False),
    "np:logical_not": (lambda: ((_u((N, N)) > 0.5,), {}), False),
    "np:gcd": (lambda: ((_idx(V, 100).astype("int32"),
                         _idx(V, 100).astype("int32")), {}), False),
    "np:lcm": (lambda: ((_idx(V, 100).astype("int32"),
                         _idx(V, 100).astype("int32")), {}), False),
    "np:ldexp": (lambda: ((_u((V,)), _idx(V, 8).astype("int32")), {}),
                 False),
    "np:divmod": (lambda: ((_u((V,)), 0.3), {}), False),
    "np:modf": (lambda: ((_u((V,)),), {}), False),
    "np:isclose": (lambda: ((_u((N, N)), _u((N, N))), {}), False),
    "np:allclose": (lambda: ((_u((N, N)), _u((N, N))), {}), False),
    "np:array_equal": (lambda: ((_u((N, N)), _u((N, N))), {}), False),
    "np:array_equiv": (lambda: ((_u((N, N)), _u((N, N))), {}), False),
    "np:clip": (lambda: ((_u((N, N)), 0.2, 0.8), {}), True),
    "np:heaviside": (lambda: ((_u((V,)), 0.5), {}), False),
    "np:copysign": (lambda: ((_u((V,)), _u((V,))), {}), False),
    "np:nextafter": (lambda: ((_u((V,)), _u((V,))), {}), False),
    "np:partition": (lambda: ((_u((V,)), 64), {}), False),
    "np:argpartition": (lambda: ((_u((V,)), 64), {}), False),
    "np:lexsort": (lambda: (((_u((V,)), _u((V,))),), {}), False),
    "np:vander": (lambda: ((_u((64,)),), {}), False),
    "np:polyval": (lambda: ((_u((8,)), _u((V,))), {}), False),
    "np:cumprod": (lambda: ((_u((N, N)),), {}), True),
    "np:nancumprod": (lambda: ((_u((N, N)),), {}), False),
    "np:nancumsum": (lambda: ((_u((N, N)),), {}), False),
    "np:resize": (lambda: ((_u((N, N)), (64, 64)), {}), False),
    "np:rot90": (lambda: ((_u((N, N)),), {}), True),
    "np:triu_indices": (lambda: ((64,), {}), False),
    "np:triu_indices_from": (lambda: ((_u((64, 64)),), {}), False),
    "np:tril_indices_from": (lambda: ((_u((64, 64)),), {}), False),
    # linalg
    "linalg:cholesky": (lambda: ((_spd(),), {}), False),
    "linalg:inv": (lambda: ((_spd(),), {}), False),
    "linalg:pinv": (lambda: ((_u((64, 32)),), {}), False),
    "linalg:det": (lambda: ((_spd(),), {}), False),
    "linalg:slogdet": (lambda: ((_spd(),), {}), False),
    "linalg:eig": (lambda: ((_spd(),), {}), False),
    "linalg:eigh": (lambda: ((_spd(),), {}), False),
    "linalg:eigvals": (lambda: ((_spd(),), {}), False),
    "linalg:eigvalsh": (lambda: ((_spd(),), {}), False),
    "linalg:qr": (lambda: ((_u((64, 64)),), {}), False),
    "linalg:svd": (lambda: ((_u((64, 64)),), {}), False),
    "linalg:solve": (lambda: ((_spd(), _u((32, 4))), {}), False),
    "linalg:lstsq": (lambda: ((_u((64, 32)), _u((64,))), {"rcond": None}),
                     False),
    "linalg:norm": (lambda: ((_u((N, N)),), {}), True),
    "linalg:cond": (lambda: ((_spd(),), {}), False),
    "linalg:matrix_rank": (lambda: ((_u((64, 64)),), {}), False),
    "linalg:matrix_power": (lambda: ((_u((64, 64)), 3), {}), False),
    "linalg:multi_dot": (lambda: (([_u((N, N)), _u((N, N)), _u((N, N))],),
                                  {}), False),
    "linalg:tensorinv": (lambda: ((_u((8, 8, 8, 8)),), {}), False),
    "linalg:tensorsolve": (lambda: ((_u((8, 8, 8, 8)), _u((8, 8))), {}),
                           False),
    "linalg:matmul": (lambda: ((_u((N, N)), _u((N, N))), {}), True),
    "linalg:potrf": (lambda: ((_spd(),), {}), False),
    # random (sampling: fwd-only)
    "random:uniform": (lambda: ((0.0, 1.0, (N, N)), {}), False),
    "random:normal": (lambda: ((0.0, 1.0, (N, N)), {}), False),
    "random:randn": (lambda: ((N, N), {}), False),
    "random:rand": (lambda: ((N, N), {}), False),
    "random:randint": (lambda: ((0, 100, (N, N)), {}), False),
    "random:random": (lambda: (((N, N),), {}), False),
    "random:random_sample": (lambda: (((N, N),), {}), False),
    "random:ranf": (lambda: (((N, N),), {}), False),
    "random:sample": (lambda: (((N, N),), {}), False),
    "random:exponential": (lambda: ((1.0, (N, N)), {}), False),
    "random:gamma": (lambda: ((2.0, 1.0, (N, N)), {}), False),
    "random:beta": (lambda: ((2.0, 3.0, (N, N)), {}), False),
    "random:chisquare": (lambda: ((2.0, (N, N)), {}), False),
    "random:poisson": (lambda: ((2.0, (N, N)), {}), False),
    "random:laplace": (lambda: ((0.0, 1.0, (N, N)), {}), False),
    "random:gumbel": (lambda: ((0.0, 1.0, (N, N)), {}), False),
    "random:logistic": (lambda: ((0.0, 1.0, (N, N)), {}), False),
    "random:lognormal": (lambda: ((0.0, 1.0, (N, N)), {}), False),
    "random:pareto": (lambda: ((2.0, (N, N)), {}), False),
    "random:power": (lambda: ((2.0, (N, N)), {}), False),
    "random:rayleigh": (lambda: ((1.0, (N, N)), {}), False),
    "random:weibull": (lambda: ((2.0, (N, N)), {}), False),
    "random:binomial": (lambda: ((10, 0.5, (N, N)), {}), False),
    "random:negative_binomial": (lambda: ((10, 0.5, (N, N)), {}), False),
    "random:geometric": (lambda: ((0.5, (N, N)), {}), False),
    "random:multinomial": (lambda: ((10, [0.25] * 4, (V,)), {}), False),
    "random:dirichlet": (lambda: (([1.0, 2.0, 3.0], (V,)), {}), False),
    "random:multivariate_normal": (
        lambda: ((mxnp.zeros(4), mxnp.array(onp.eye(4, dtype="float32")),
                  (V,)), {}), False),
    "random:choice": (lambda: ((V, (64,)), {}), False),
    "random:permutation": (lambda: ((V,), {}), False),
    "random:shuffle": (lambda: ((_u((V,)),), {}), False),
    "random:bernoulli": (lambda: ((0.5,), {"size": (N, N)}), False),
    "random:triangular": (lambda: ((0.0, 0.5, 1.0, (N, N)), {}), False),
    "random:f": (lambda: ((2.0, 3.0, (N, N)), {}), False),
    "random:standard_t": (lambda: ((3.0, (N, N)), {}), False),
    "random:standard_cauchy": (lambda: (((N, N),), {}), False),
    "random:standard_exponential": (lambda: (((N, N),), {}), False),
    "random:standard_gamma": (lambda: ((2.0, (N, N)), {}), False),
    "random:standard_normal": (lambda: (((N, N),), {}), False),
    "random:vonmises": (lambda: ((0.0, 1.0, (N, N)), {}), False),
    "random:wald": (lambda: ((1.0, 1.0, (N, N)), {}), False),
    "random:zipf": (lambda: ((2.0, (N, N)), {}), False),
    "random:hypergeometric": (lambda: ((50, 50, 10, (N, N)), {}), False),
    "random:logseries": (lambda: ((0.5, (N, N)), {}), False),
    "random:noncentral_chisquare": (lambda: ((2.0, 1.0, (N, N)), {}),
                                    False),
    "random:noncentral_f": (lambda: ((2.0, 3.0, 1.0, (N, N)), {}), False),
    # npx
    "npx:fully_connected": (
        lambda: ((_u((128, N)), _u((N, N)), _u((N,))), {"num_hidden": N}),
        True),
    "npx:convolution": (
        lambda: ((_u(IMG), _u((16, 16, 3, 3))),
                 {"kernel": (3, 3), "pad": (1, 1), "num_filter": 16,
                  "no_bias": True}), True),
    "npx:deconvolution": (
        lambda: ((_u(IMG), _u((16, 16, 3, 3))),
                 {"kernel": (3, 3), "num_filter": 16, "no_bias": True}),
        False),
    "npx:pooling": (
        lambda: ((_u(IMG),), {"kernel": (2, 2), "stride": (2, 2)}), True),
    "npx:activation": (lambda: ((_u((N, N)),), {"act_type": "relu"}), True),
    "npx:batch_norm": (
        lambda: ((_u(IMG), _u((16,)), _u((16,)), _u((16,)), _u((16,))),
                 {"use_global_stats": True}), True),
    "npx:layer_norm": (
        lambda: ((_u((N, N)), _u((N,)), _u((N,))), {}), True),
    "npx:group_norm": (
        lambda: ((_u(IMG), _u((4,)), _u((4,))), {"num_groups": 4}), False),
    "npx:instance_norm": (
        lambda: ((_u(IMG), _u((16,)), _u((16,))), {}), False),
    "npx:l2_normalization": (lambda: ((_u((N, N)),), {}), False),
    "npx:lrn": (lambda: ((_u(IMG),), {"nsize": 5}), False),
    "npx:dropout": (lambda: ((_u((N, N)),), {"p": 0.5}), False),
    "npx:softmax": (lambda: ((_u((N, N)),), {}), True),
    "npx:log_softmax": (lambda: ((_u((N, N)),), {}), True),
    "npx:masked_softmax": (
        lambda: ((_u((N, N)), _u((N, N)) > 0.5), {}), False),
    "npx:softmin": (lambda: ((_u((N, N)),), {}), False),
    "npx:relu": (lambda: ((_u((N, N)),), {}), True),
    "npx:sigmoid": (lambda: ((_u((N, N)),), {}), True),
    "npx:smooth_l1": (lambda: ((_u((N, N)),), {}), False),
    "npx:embedding": (
        lambda: ((_idx(V, 1000), _u((1000, 64))),
                 {"input_dim": 1000, "output_dim": 64}), False),
    "npx:topk": (lambda: ((_u((N, N)),), {"k": 8}), False),
    "npx:pick": (lambda: ((_u((N, N)), _idx(N, N)), {}), False),
    "npx:one_hot": (lambda: ((_idx(V, 64),), {"depth": 64}), False),
    "npx:arange_like": (lambda: ((_u((N, N)),), {}), False),
    "npx:batch_dot": (lambda: ((_u((16, 64, 64)), _u((16, 64, 64))), {}),
                      True),
    "npx:erf": (lambda: ((_u((N, N)),), {}), True),
    "npx:erfinv": (lambda: ((_u((N, N)) * 0.9,), {}), False),
    "npx:reshape": (lambda: ((_u((N, N)), (-1,)), {}), False),
    "npx:reshape_like": (lambda: ((_u((N, N)), _u((N * N,))), {}), False),
    "npx:shape_array": (lambda: ((_u((N, N)),), {}), False),
    "npx:slice": (lambda: ((_u((N, N)),),
                           {"begin": (0, 0), "end": (64, 64)}), False),
    "npx:slice_axis": (lambda: ((_u((N, N)),),
                                {"axis": 0, "begin": 0, "end": 64}), False),
    "npx:slice_like": (lambda: ((_u((N, N)), _u((64, 64))), {}), False),
    "npx:gather_nd": (
        lambda: ((_u((N, N)), _idx(64, N).reshape(1, 64)), {}), False),
    "npx:sequence_mask": (
        lambda: ((_u((35, 32, 64)), mxnp.array([20.0] * 32)),
                 {"use_sequence_length": True}), False),
    "npx:sequence_last": (
        lambda: ((_u((35, 32, 64)), mxnp.array([20.0] * 32)),
                 {"use_sequence_length": True}), False),
    "npx:sequence_reverse": (
        lambda: ((_u((35, 32, 64)), mxnp.array([20.0] * 32)),
                 {"use_sequence_length": True}), False),
    "npx:rnn": None,         # exercised via the gluon.rnn bench row
    "npx:foreach": None,     # control flow: covered by bench_infer scan
    "npx:while_loop": None,
    "npx:cond": None,
    "npx:flash_attention": (
        lambda: ((_u((4, 8, 128, 64)), _u((4, 8, 128, 64)),
                  _u((4, 8, 128, 64))), {}), True),
    "npx:bias_gelu": (
        lambda: ((_u((128, N)), _u((N,))), {}), True),
    "npx:bias_dropout_residual": (
        lambda: ((_u((128, N)), _u((N,)), _u((128, N))), {"p": 0.1}), True),
    "npx:interleaved_matmul_selfatt_qk": (
        lambda: ((_u((128, 8, 3 * 64)),), {"heads": 8}), False),
    "npx:interleaved_matmul_selfatt_valatt": (
        lambda: ((_u((128, 8, 3 * 64)), _u((8 * 8, 128, 128))),
                 {"heads": 8}), False),
    "npx:cast": (lambda: ((_u((N, N)),), {"dtype": "float16"}), False),
    "npx:amp_cast": (lambda: ((_u((N, N)),), {"dtype": "float16"}), False),
    "npx:amp_multicast": None,
    "npx:all_finite": (lambda: ((_u((N, N)),), {}), False),
    "npx:norm": (lambda: ((_u((N, N)),), {}), False),
    "npx:ctc_loss": None,
}


def calibrate(repeats=5, inner=8):
    """Median ms of a fixed PURE-NUMPY workload (matmul + elementwise) —
    a machine/load probe, deliberately untouched by any framework code
    path.  The perf gate (tests/test_opperf_gate.py) divides its op
    ratios by (calibrate() now / the committed value in
    OPPERF_CALIB.json), so a loaded CI box — where every wall-clock
    measurement inflates together — no longer reads as a framework
    regression, while a real eager-path regression (framework-only, the
    5-20x class) still fails the normalized bars."""
    a = onp.random.RandomState(0).rand(256, 256).astype("float32")
    samples = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(inner):
            b = a @ a
            b = onp.exp(b * 1e-3) + a
            b.sum()
        samples.append((time.perf_counter() - t0) / inner * 1e3)
    return statistics.median(samples)


def enumerate_ops():
    """(qualified_name, callable) across the live op namespaces."""
    from mxnet_tpu.contrib import ops as cops
    spaces = [("np", mxnp), ("npx", npx), ("linalg", mxnp.linalg),
              ("random", mxnp.random), ("contrib", cops)]
    out = []
    for prefix, mod in spaces:
        for name in sorted(dir(mod)):
            if name.startswith("_") or name in EXCLUDE.get(prefix, ()):
                continue
            fn = getattr(mod, name)
            if not callable(fn) or inspect.isclass(fn):
                continue
            out.append(("%s:%s" % (prefix, name), fn))
    return out


# generic probes tried in order when no override exists
GENERIC_PROBES = [
    (lambda: ((_u((N, N)),), {}), True),                 # unary float
    (lambda: ((_u((N, N)), _u((N, N))), {}), True),      # binary float
    (lambda: ((_u((N, N)), 2.0), {}), True),             # array + scalar
    (lambda: ((_u((V,)),), {}), True),                   # unary vector
]


def synthesize(qual, fn):
    """Return (args_thunk, needs_grad) or None if unsupported."""
    if qual in OVERRIDES:
        return OVERRIDES[qual]
    for thunk, grad in GENERIC_PROBES:
        try:
            args, kwargs = thunk()
            out = fn(*args, **kwargs)
            leaf = out[0] if isinstance(out, (tuple, list)) and out else out
            if isinstance(leaf, ndarray):
                leaf.wait_to_read()
            return (thunk, grad)
        except Exception:
            continue
    return None


# ---------------------------------------------------------------------------
# measurement
# ---------------------------------------------------------------------------
def _sync(out):
    if isinstance(out, (tuple, list)):
        for o in out:
            _sync(o)
    elif isinstance(out, ndarray):
        out.wait_to_read()


def bench_op(fn, args_thunk, needs_grad, warmup=3, iters=10, windows=3,
             agg="median"):
    """Median across windows of (window_time / iters); one sync per
    window (eager steady state is async dispatch, not host RTT).
    ``agg='min'`` takes the best window instead — interference (GC
    pauses, a competing lane's burst) only ever ADDS time, so min-of-N
    approaches the true dispatch cost; the perf gate's retry uses it."""
    from mxnet_tpu import engine
    pick = min if agg == "min" else statistics.median
    args, kwargs = args_thunk()
    nd_args = []
    for a in args:  # include arrays nested in list args (concat family)
        if isinstance(a, ndarray):
            nd_args.append(a)
        elif isinstance(a, (list, tuple)):
            nd_args.extend(x for x in a if isinstance(x, ndarray))

    fwd_samples = []
    with engine.bulk(1):
        for _ in range(warmup):
            out = fn(*args, **kwargs)
        _sync(out)
        for _ in range(windows):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(*args, **kwargs)
            _sync(out)
            fwd_samples.append((time.perf_counter() - t0) / iters * 1e3)
    fwd_ms = pick(fwd_samples)

    bwd_ms = None
    if needs_grad and nd_args:
        for a in nd_args:
            a.attach_grad()

        def run_bwd():
            with autograd.record():
                o = fn(*args, **kwargs)
                if isinstance(o, (tuple, list)):
                    o = o[0]
                loss = o.sum()
            loss.backward()
        try:
            bwd_samples = []
            for _ in range(warmup):
                run_bwd()
            nd_args[0].grad.wait_to_read()
            for _ in range(windows):
                t0 = time.perf_counter()
                for _ in range(iters):
                    run_bwd()
                nd_args[0].grad.wait_to_read()
                bwd_samples.append((time.perf_counter() - t0) / iters * 1e3)
            bwd_ms = pick(bwd_samples)
        except Exception:
            bwd_ms = None
    return fwd_ms, bwd_ms


def run(names=None, iters=10, probe_only=False, verbose=True,
        platform=None, windows=3, agg="median"):
    if platform:
        # must precede first backend use (the axon sitecustomize ignores
        # JAX_PLATFORMS, so the config API is the only reliable switch)
        jax.config.update("jax_platforms", platform)
    mx.random.seed(0)
    ops = enumerate_ops()
    if names:
        sel = set(names)
        ops = [(q, f) for q, f in ops if q in sel or q.split(":")[1] in sel]
    rows, skipped = [], []
    for qual, fn in ops:
        spec = synthesize(qual, fn)
        if spec is None:
            skipped.append(qual)
            continue
        if probe_only:
            rows.append({"op": qual})
            continue
        try:
            fwd, bwd = bench_op(fn, spec[0], spec[1], iters=iters,
                                windows=windows, agg=agg)
        except Exception as e:
            skipped.append("%s (%s)" % (qual, type(e).__name__))
            continue
        rows.append({"op": qual, "fwd_ms": round(fwd, 4),
                     "fwd_bwd_ms": round(bwd, 4) if bwd else None})
        if verbose:
            print("%-40s %10.4f %10s" % (
                qual, fwd, "%.4f" % bwd if bwd else "n/a"), flush=True)
    return rows, skipped


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", default=None, help="comma-separated subset")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--json", default=None)
    ap.add_argument("--probe-only", action="store_true",
                    help="report op coverage without timing")
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu) before first use")
    args = ap.parse_args()

    names = args.ops.split(",") if args.ops else None
    rows, skipped = run(names, iters=args.iters,
                        probe_only=args.probe_only,
                        platform=args.platform)
    print("covered %d ops, skipped %d" % (len(rows), len(skipped)))
    if skipped:
        print("skipped:", ", ".join(sorted(skipped)))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rows, f, indent=1)
        print("wrote", args.json)


if __name__ == "__main__":
    main()
