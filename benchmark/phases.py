#!/usr/bin/env python
"""Per-phase breakdown of the ResNet-50 bf16 and LSTM training steps.

Decomposes the bench's flagship training step into measurable phases —
forward, forward+backward, optimizer-only, full step — each timed as its
own jitted program with fused windows (one dispatch + one scalar fetch
per window; the tunnel charges ~6 ms/dispatch, ~110 ms/fetch).  Emits
benchmark/PHASES.json including compiled FLOP counts (XLA cost
analysis), achieved FLOP/s, and MFU per phase.

Usage: python benchmark/phases.py [--json benchmark/PHASES.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

import jax
import jax.numpy as jnp


def _scalarize(out):
    """Reduce any output pytree to one scalar so the sync is a real
    value FETCH — through the axon tunnel block_until_ready is not a
    true sync; only fetching data is."""
    leaves = jax.tree.leaves(out)
    small = min(leaves, key=lambda l: getattr(l, "size", 1))
    return jnp.sum(small.astype(jnp.float32)) if hasattr(small, "astype") \
        else small


def _wtime(fn, *args, iters=1, windows=3):
    """Best-of-windows wall time per call; syncs by FETCHING a scalar
    derived from the result (see _scalarize)."""
    float(jax.device_get(_scalarize(fn(*args))))
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        float(jax.device_get(_scalarize(out)))
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def _cost(jfn, *args):
    try:
        an = jfn.lower(*args).compile().cost_analysis()
        if isinstance(an, list):
            an = an[0]
        return {"flops": an.get("flops"),
                "bytes": an.get("bytes accessed")}
    except Exception:
        return {"flops": None, "bytes": None}


def _peak():
    try:
        from mxnet_tpu.profiler import chip_spec
        return chip_spec().get("peak_flops_bf16")
    except Exception:
        return None



def _bf16_params(params):
    """Cast float32 param values to bf16 (bench methodology for the
    transformer/LSTM rows)."""
    return {k: (p._data._data.astype(jnp.bfloat16)
                if p._data._data.dtype == jnp.float32 else p._data._data)
            for k, p in params.items()}


SPEC_BW = 819e9  # v5e HBM bandwidth (bytes/s)


def _roofline_bound(cost, t, peak):
    """Adjudicate compute-/bandwidth-/latency-bound from XLA cost
    analysis + measured time (shared by the per-model phase fns)."""
    if not cost.get("bytes") or not peak or not t:
        return None
    cf = cost["flops"] / t / peak
    cb = cost["bytes"] / t / SPEC_BW
    return {"pct_compute_roofline": round(cf, 3),
            "pct_bandwidth_roofline": round(cb, 3),
            "bound": ("latency" if max(cf, cb) < 0.5 else
                      ("compute" if cf > cb else "bandwidth"))}


def resnet_phases(batch=256, dtype="bfloat16", layout="NCHW"):
    import mxnet_tpu as mx
    from mxnet_tpu import np as mxnp
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    from mxnet_tpu.parallel import DataParallelTrainer, Mesh

    mx.random.seed(0)
    net = resnet50_v1(classes=1000, layout=layout)
    net.initialize(mx.init.Xavier())
    shape = ((batch, 3, 224, 224) if layout == "NCHW"
             else (batch, 224, 224, 3))
    x = mxnp.random.uniform(size=shape)
    y = mxnp.random.randint(0, 1000, size=(batch,))
    net(x[:1])
    if dtype != "float32":
        net.cast(dtype)
        x = x.astype(dtype)
    loss_obj = SoftmaxCrossEntropyLoss()

    def loss_fn(out, label):
        return loss_obj(out.astype("float32"), label)

    mesh = Mesh(onp.array(jax.devices()[:1]), ("dp",))
    trainer = DataParallelTrainer(net, loss_fn, "sgd",
                                  {"learning_rate": 0.05, "momentum": 0.9},
                                  mesh=mesh)
    state = trainer.init_state()
    step = trainer.build_step(donate=False)  # keep state reusable
    key = jax.random.key(0)
    xv, yv = x._data, y._data

    # --- full step
    full_t = _wtime(lambda: step(state, xv, yv, key, 0.05), iters=8)
    full_cost = _cost(step, state, xv, yv, key, 0.05)

    # --- fwd+bwd only (no optimizer): value_and_grad of the same loss
    from mxnet_tpu.parallel import functionalize
    fn, params = functionalize(net, train=True)
    pvals = {k: p._data._data for k, p in params.items()}
    import mxnet_tpu.autograd as ag
    from mxnet_tpu.ndarray import _wrap_value

    grad_names = [k for k, p in params.items() if p.grad_req != "null"]

    def loss_of(diff, kkey):
        fullp = dict(pvals)
        fullp.update(diff)
        out, aux = fn(fullp, xv, key=kkey)
        with ag._RecordingStateScope(False, True):
            l = loss_fn(_wrap_value(out), _wrap_value(yv))
        return jnp.mean(l._data)

    diff = {k: pvals[k] for k in grad_names}
    vg = jax.jit(lambda d, kk: jax.value_and_grad(loss_of)(d, kk))
    fwd_bwd_t = _wtime(lambda: vg(diff, key), iters=8)
    fwd_bwd_cost = _cost(vg, diff, key)

    # --- fwd only
    fw = jax.jit(lambda d, kk: loss_of(d, kk))
    fwd_t = _wtime(lambda: fw(diff, key), iters=8)
    fwd_cost = _cost(fw, diff, key)

    # --- optimizer only: sgd-momentum over all trainable tensors
    grads = {k: jnp.ones_like(v) * 1e-4 for k, v in diff.items()}
    slots = {k: jnp.zeros(v.shape, jnp.float32) for k, v in diff.items()}

    def opt(params_d, grads_d, slots_d):
        new_p, new_s = {}, {}
        for k in params_d:
            g = grads_d[k].astype(jnp.float32)
            m = 0.9 * slots_d[k] - 0.05 * g
            new_s[k] = m
            new_p[k] = (params_d[k].astype(jnp.float32)
                        + m).astype(params_d[k].dtype)
        return new_p, new_s

    jopt = jax.jit(opt)
    opt_t = _wtime(lambda: jopt(diff, grads, slots), iters=8)
    opt_cost = _cost(jopt, diff, grads, slots)

    peak = _peak()

    def mfu(model_flops, t):
        return round(model_flops / t / peak, 4) if (peak and t) else None

    model_flops = 3 * 8.2e9 * batch  # fwd+bwd+update convention

    # roofline adjudication: is the step compute- or bandwidth-bound?
    # (richer fields than _roofline_bound: achieved bandwidth matters
    # for the resnet story)
    roofline = None
    if fwd_bwd_cost.get("bytes") and peak:
        by = fwd_bwd_cost["bytes"]
        fl = fwd_bwd_cost["flops"]
        intensity = fl / by
        balance = peak / SPEC_BW
        roofline = {
            "achieved_bw_GBps": round(by / fwd_bwd_t / 1e9, 1),
            "spec_bw_GBps": round(SPEC_BW / 1e9, 1),
            "pct_of_spec_bw": round(by / fwd_bwd_t / SPEC_BW, 3),
            "arith_intensity_F_per_B": round(intensity, 1),
            "chip_balance_F_per_B": round(balance, 1),
            "bound": ("bandwidth" if intensity < balance else "compute"),
        }

    return {
        "config": {"model": "resnet50_v1", "batch": batch, "dtype": dtype,
                   "layout": layout},
        "roofline": roofline,
        "phases": {
            "full_step": {"ms": round(full_t * 1e3, 2), **full_cost,
                          "mfu_model": mfu(model_flops, full_t)},
            "fwd_bwd": {"ms": round(fwd_bwd_t * 1e3, 2), **fwd_bwd_cost,
                        "mfu_model": mfu(model_flops, fwd_bwd_t)},
            "fwd": {"ms": round(fwd_t * 1e3, 2), **fwd_cost,
                    "mfu_model": mfu(8.2e9 * batch, fwd_t)},
            "optimizer": {"ms": round(opt_t * 1e3, 2), **opt_cost},
            "derived_bwd_ms": round((fwd_bwd_t - fwd_t) * 1e3, 2),
            "derived_opt_overhead_ms": round((full_t - fwd_bwd_t) * 1e3, 2),
        },
        "peak_flops_bf16": peak,
        "imgs_per_sec_full": round(batch / full_t, 1),
    }


def bert_phases(B=None, L=128):
    """BERT-base bf16 fwd+bwd roofline adjudication (same harness as the
    bench's config 3: flash attention + fused epilogues on).  On a CPU-only
    box the row still lands (scaled-down batch, backend recorded, no MFU
    — there is no meaningful bf16 peak), so the committed PHASES.json is
    honest about where each number came from."""
    import mxnet_tpu as mx
    from mxnet_tpu import np as mxnp
    from mxnet_tpu.models.bert import bert_base
    from mxnet_tpu.parallel import functionalize
    from mxnet_tpu.ops.pallas import epilogue as _epi

    backend = jax.default_backend()
    on_chip = backend != "cpu"
    if B is None:
        B = 32 if on_chip else 2
    K = 8 if on_chip else 2

    mx.random.seed(0)
    net = bert_base(max_length=max(L, 128))
    net.initialize(mx.init.Xavier())
    tokens = mxnp.random.randint(0, 30000, size=(B, L))
    net(tokens)
    counts0 = dict(_epi.trace_counts)
    fn, params = functionalize(net, train=True)
    pvals = _bf16_params(params)
    labels = jax.random.randint(jax.random.key(0), (B, L), 0, 30000)
    tok = tokens._data

    def loss_of(pv, i):
        out, _aux = fn(pv, tok, key=jax.random.fold_in(jax.random.key(2), i))
        # out = (mlm_logits (B, L, vocab), nsp_logits): train on the MLM
        # head the model already carries — no synthetic head, so the
        # compiled FLOPs match the 6ND model-FLOPs convention
        mlm = out[0] if isinstance(out, (tuple, list)) else out
        lp = jax.nn.log_softmax(mlm.astype(jnp.float32), axis=-1)
        return -jnp.mean(jnp.take_along_axis(lp, labels[..., None], -1))

    def chained(pv):
        def body(i, carry):
            l, g = jax.value_and_grad(loss_of)(carry, i)
            return jax.tree.map(
                lambda p, gg: p - 0.01 * gg.astype(p.dtype), carry, g)
        out = jax.lax.fori_loop(0, K, body, pv)
        return loss_of(out, K)

    cj = jax.jit(chained)
    fb_t = _wtime(lambda: cj(pvals), iters=1) / K
    fb_cost = _cost(jax.jit(lambda pv: jax.value_and_grad(loss_of)(pv, 0)),
                    pvals)
    # the row documents the FUSED fast path; assert it actually traced
    fused_traced = {k: _epi.trace_counts[k] - counts0[k] for k in counts0}
    from mxnet_tpu.ops.pallas.epilogue import fuse_epilogue_enabled
    if fuse_epilogue_enabled():
        assert fused_traced["bias_gelu"] > 0 \
            and fused_traced["bias_dropout_residual"] > 0, fused_traced
    peak = _peak()
    model_flops = (6 * 110e6 + (12 * L * 768 * 12 if L > 512 else 0)) * B * L
    bound = _roofline_bound(fb_cost, fb_t, peak)
    return {
        "config": {"model": "bert_base", "B": B, "L": L,
                   "dtype": "bfloat16", "backend": backend,
                   "fused_epilogue": fuse_epilogue_enabled()},
        "fused_epilogue_ops_traced": fused_traced,
        "roofline": bound,
        "phases": {"fwd_bwd": {"ms": round(fb_t * 1e3, 2), **fb_cost,
                               "mfu_model": (round(model_flops / fb_t / peak,
                                                   4) if peak else None)}},
        "tokens_per_sec_fwd_bwd": round(B * L / fb_t, 1),
    }


def lstm_phases(B=32, T=35):
    import mxnet_tpu as mx
    from mxnet_tpu import np as mxnp
    from mxnet_tpu.gluon import nn, rnn, HybridBlock
    from mxnet_tpu.parallel import functionalize

    vocab, emsize, nhid, nlayers = 10000, 650, 650, 2

    class WordLM(HybridBlock):
        def __init__(self):
            super().__init__()
            self.embed = nn.Embedding(vocab, emsize)
            self.lstm = rnn.LSTM(nhid, num_layers=nlayers, layout="NTC",
                                 input_size=emsize)
            self.decoder = nn.Dense(vocab, flatten=False, in_units=nhid)

        def forward(self, x):
            return self.decoder(self.lstm(self.embed(x)))

    mx.random.seed(0)
    net = WordLM()
    net.initialize(mx.init.Xavier())
    tokens = mxnp.random.randint(0, vocab, size=(B, T))
    net(tokens)
    fn, params = functionalize(net, train=True)
    pvals = _bf16_params(params)
    labels = jax.random.randint(jax.random.key(0), (B, T), 0, vocab)
    tok = tokens._data

    def loss_of(pv):
        out, _aux = fn(pv, tok)
        lp = jax.nn.log_softmax(out.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(lp, labels[..., None], -1))

    # per-step programs are ~ms-scale: chain K steps INSIDE one program
    # (lax.fori_loop) so the tunnel's ~6ms dispatch charge amortizes and
    # the number is pure device time
    K = 16

    def chained(pv):
        def body(_, carry):
            l, g = jax.value_and_grad(loss_of)(carry)
            return jax.tree.map(
                lambda p, gg: p - 0.01 * gg.astype(p.dtype), carry, g)
        out = jax.lax.fori_loop(0, K, body, pv)
        return loss_of(out)

    cj = jax.jit(chained)
    fb_t = _wtime(lambda: cj(pvals), iters=1) / K
    fb_cost = _cost(jax.jit(lambda pv: jax.value_and_grad(loss_of)(pv)),
                    pvals)

    def chained_fwd(pv):
        def body(_, acc):
            return acc + loss_of(pv)
        return jax.lax.fori_loop(0, K, body, jnp.zeros((), jnp.float32))

    fwd_t = _wtime(lambda: jax.jit(chained_fwd)(pvals), iters=1) / K

    # decoder matmul alone (the FLOPs-dominant piece), K-chained
    dw = pvals["decoder.weight"]
    emb = jax.random.normal(jax.random.key(1), (B * T, nhid),
                            jnp.bfloat16)

    def chained_dec(e, w):
        def body(_, acc):
            return acc + jnp.sum((e @ w.T).astype(jnp.float32))
        return jax.lax.fori_loop(0, K, body, jnp.zeros((), jnp.float32))

    dec_t = _wtime(lambda: jax.jit(chained_dec)(emb, dw), iters=1) / K

    peak = _peak()
    model_flops = 6 * 13.3e6 * B * T
    # adjudication: compute 61GF/8ms = ~4% of MXU peak and bytes
    # 1.45GB/8ms = ~22% of HBM bandwidth — NEITHER roofline binds; the
    # step is LATENCY-bound on the ~70 serial scan iterations (fwd+bwd)
    # of small (B=32) cells.  This is inherent to the reference workload
    # shape (bptt=35, bs=32), not schedulable work.
    bound = _roofline_bound(fb_cost, fb_t, peak)
    return {
        "config": {"model": "lstm_lm_2x650", "B": B, "T": T,
                   "dtype": "bfloat16"},
        "roofline": bound,
        "phases": {
            "fwd": {"ms": round(fwd_t * 1e3, 3)},
            "fwd_bwd": {"ms": round(fb_t * 1e3, 3), **fb_cost,
                        "mfu_model": (round(model_flops / fb_t / peak, 4)
                                      if peak else None)},
            "decoder_matmul": {"ms": round(dec_t * 1e3, 3)},
        },
        "tokens_per_sec_fwd_bwd": round(B * T / fb_t, 1),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "PHASES.json"))
    ap.add_argument("--only", default=None,
                    choices=[None, "resnet", "resnet_nhwc", "lstm",
                             "bert"])
    args = ap.parse_args()
    # --only must MERGE into the committed file, not clobber the other
    # models' rows
    out = {}
    if args.only is not None and os.path.exists(args.json):
        try:
            with open(args.json) as f:
                out = json.load(f)
        except Exception:
            out = {}
    if args.only in (None, "resnet"):
        out["resnet50_bf16"] = resnet_phases()
        print(json.dumps(out["resnet50_bf16"], indent=1), flush=True)
    if args.only in (None, "resnet_nhwc"):
        out["resnet50_bf16_nhwc"] = resnet_phases(layout="NHWC")
        print(json.dumps(out["resnet50_bf16_nhwc"], indent=1), flush=True)
    if args.only in (None, "lstm"):
        out["lstm_lm"] = lstm_phases()
        print(json.dumps(out["lstm_lm"], indent=1), flush=True)
    if args.only in (None, "bert"):
        out["bert_base"] = bert_phases()
        print(json.dumps(out["bert_base"], indent=1), flush=True)
    with open(args.json, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", args.json)


if __name__ == "__main__":
    main()
