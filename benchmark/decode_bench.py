"""Input-pipeline decode benchmark (VERDICT r1 item #6).

Measures ImageRecordIter throughput (native libjpeg decode on the host
engine worker pool, GIL released per decode) against the pure-Python
PIL decode path on the same .rec file.  Prints one JSON line; run with
`python benchmark/decode_bench.py` and commit the number.
"""
from __future__ import annotations

import io as _pyio
import json
import os
import sys
import tempfile
import time

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def make_rec(path, n=256, hw=256):
    from PIL import Image
    from mxnet_tpu import recordio
    rng = onp.random.RandomState(0)
    w = recordio.MXIndexedRecordIO(path + ".idx", path, "w")
    for i in range(n):
        arr = rng.randint(0, 255, (hw, hw, 3), dtype=onp.uint8)
        buf = _pyio.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG", quality=90)
        header = recordio.IRHeader(0, float(i % 10), i, 0)
        w.write_idx(i, recordio.pack(header, buf.getvalue()))
    w.close()


def bench_imagerecorditer(path, batch_size=32, resize=224, shape=224):
    from mxnet_tpu.io import ImageRecordIter
    it = ImageRecordIter(path_imgrec=path, path_imgidx=path + ".idx",
                         data_shape=(3, shape, shape),
                         batch_size=batch_size, resize=resize,
                         rand_crop=True, rand_mirror=True,
                         mean_r=123.68, mean_g=116.78, mean_b=103.94,
                         std_r=58.4, std_g=57.12, std_b=57.38)
    n = 0
    # warmup epoch
    for batch in it:
        n += batch.data[0].shape[0]
    it.reset()
    t0 = time.perf_counter()
    m = 0
    for batch in it:
        m += batch.data[0].shape[0]
    dt = time.perf_counter() - t0
    return m / dt


def bench_python_pil(path, batch_size=32, resize=224, shape=224):
    """The same pipeline decoded by PIL in a single-threaded loop (what a
    naive Python DataLoader does per worker)."""
    from PIL import Image
    from mxnet_tpu import recordio
    reader = recordio.MXRecordIO(path, "r")
    t0 = time.perf_counter()
    m = 0
    rng = onp.random.RandomState(0)
    while True:
        rec = reader.read()
        if rec is None:
            break
        _h, payload = recordio.unpack(rec)
        img = onp.asarray(Image.open(_pyio.BytesIO(payload)))
        ih, iw = img.shape[:2]
        s = resize / min(ih, iw)
        img = onp.asarray(Image.fromarray(img).resize(
            (int(iw * s + 0.5), int(ih * s + 0.5))))
        ih, iw = img.shape[:2]
        y = rng.randint(0, ih - shape + 1)
        x = rng.randint(0, iw - shape + 1)
        img = img[y:y + shape, x:x + shape].astype(onp.float32)
        img = (img - [123.68, 116.78, 103.94]) / [58.4, 57.12, 57.38]
        _ = onp.transpose(img, (2, 0, 1))
        m += 1
    dt = time.perf_counter() - t0
    reader.close()
    return m / dt


def main():
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "bench.rec")
        make_rec(path)
        native = bench_imagerecorditer(path)
        python = bench_python_pil(path)
    print(json.dumps({
        "metric": "imagerecorditer_decode_imgs_per_sec",
        "value": round(native, 1),
        "unit": "img/s",
        "python_pil_baseline": round(python, 1),
        "speedup_vs_python": round(native / python, 2),
    }))


if __name__ == "__main__":
    main()
