"""Per-step dispatch-latency microbench for the persistent fused-cell
kernels (ROADMAP item 4 / ISSUE 8).

The latency-bound workloads this repo cares about are serial towers of
small steps: the LSTM cell loop (~T sequential cell iterations per
training step) and the LLM decode step (one token per sequence per
iteration).  This bench reports, for each, the two numbers that matter
and that CI can gate on without opperf-style flake risk:

- **launches/step** — a STATIC census of launch-class primitives in the
  traced step program (``ops/pallas/fused_cell.count_launches``:
  matmuls, gathers/scatters, reductions, pallas calls; elementwise
  chains fuse away).  Deterministic and load-independent; the tier-1
  gate in tests/test_fused_cell.py asserts the fused paths' counts.
- **host-gap μs/step** — measured wall time per step of the jitted
  program (informational: timing IS load-dependent, so only the counts
  are gated).

Run: ``python benchmark/steplat.py`` → one JSON line.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if "jax" not in sys.modules and "xla_force_host_platform_device_count" \
        not in os.environ.get("XLA_FLAGS", ""):
    # the sharded census needs a multi-device mesh; carve 8 virtual CPU
    # devices (affects only the host platform — TPU backends unchanged)
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

import jax
import jax.numpy as jnp
import numpy as onp


def _median_wall_us(fn, *args, iters=10, per=1):
    """Median wall μs of ``fn(*args)`` over ``iters`` calls, divided by
    ``per`` (steps amortized inside one call)."""
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        samples.append((time.perf_counter() - t0) * 1e6 / per)
    samples.sort()
    return round(samples[len(samples) // 2], 2)


def lstm_steplat(T=35, B=32, I=128, H=128, L=2, measure=True, iters=10,
                 fused_mode=None):
    """LSTM cell-step dispatch census + latency, scan vs fused.

    ``fused_mode`` None → 'interpret' on CPU (counts identical to the
    compiled kernel; timings meaningless and skipped unless measure).
    Returns {scan: {...}, fused: {...}} with launches_per_step,
    launches_total, pallas_total, and host_gap_us_per_step when
    measured.
    """
    from mxnet_tpu.ops import rnn as oprnn
    from mxnet_tpu.ops.pallas import fused_cell as fc

    if fused_mode is None:
        fused_mode = ("compiled" if jax.default_backend() != "cpu"
                      else "interpret")
    rng = jax.random.key(0)
    ks = jax.random.split(rng, 3)
    x = jax.random.normal(ks[0], (T, B, I), jnp.float32)
    params = jax.random.normal(
        ks[1], (oprnn.param_size("lstm", I, H, L),), jnp.float32) * 0.1
    h0 = jnp.zeros((L, B, H), jnp.float32)
    c0 = jnp.zeros((L, B, H), jnp.float32)

    def fwd(fused):
        def f(x, params, h0, c0):
            out, hT, cT = oprnn.rnn_forward(
                x, params, h0, c0, "lstm", H, L, fused=fused)
            return out
        return f

    out = {}
    for name, fused in (("scan", None), ("fused", fused_mode)):
        f = fwd(fused)
        jaxpr = jax.make_jaxpr(f)(x, params, h0, c0)
        total = fc.count_launches(jaxpr)
        pallas = fc.count_pallas_calls(jaxpr)
        row = {"launches_total": int(total),
               "launches_per_step": round(total / T, 3),
               "pallas_total": int(pallas)}
        # timing the interpret lane is meaningless (python-level grid)
        if measure and (fused is None or fused == "compiled"):
            jf = jax.jit(f)
            jax.block_until_ready(jf(x, params, h0, c0))  # compile
            row["host_gap_us_per_step"] = _median_wall_us(
                jf, x, params, h0, c0, iters=iters, per=T)
        out[name] = row
    out["T"] = T
    out["layers"] = L
    return out


def decode_steplat(measure=True, iters=10, fused_mode=None, slots=8,
                   page_size=8, layer_group=0, model_kw=None):
    """LLM decode-step dispatch census + latency, per-op tower vs the
    fused layer-group kernel.  Counts come from
    models.decoder.decode_launch_stats (the same census the engine
    exports in its metrics)."""
    from mxnet_tpu.models import decoder as dec

    if fused_mode is None:
        fused_mode = ("compiled" if jax.default_backend() != "cpu"
                      else "interpret")
    kw = dict(vocab_size=128, num_layers=2, units=64, hidden_size=128,
              num_heads=4, num_kv_heads=2, max_length=128)
    kw.update(model_kw or {})
    lm = dec.decoder_tiny_lm(seed=0, **kw)
    cfg = lm.config
    params = lm.jax_params()
    pps = (kw["max_length"] + page_size - 1) // page_size
    total = slots * pps + 1

    out = {}
    for name, fused in (("tower", False), ("fused", True)):
        stats = dec.decode_launch_stats(
            params, cfg, page_size, slots, pps, total, fused=fused,
            layer_group=layer_group, mode=fused_mode)
        row = dict(stats)
        if measure and (not fused or fused_mode == "compiled"):
            fn = (dec.make_decode_step_fused(cfg, page_size, layer_group,
                                             fused_mode) if fused
                  else dec.make_decode_step(cfg, page_size))
            shape = (cfg.num_layers, cfg.num_kv_heads, total, page_size,
                     cfg.head_dim)

            def run(fn=fn, shape=shape):
                kp = jnp.zeros(shape, jnp.float32)
                vp = jnp.zeros(shape, jnp.float32)
                return fn(params, kp, vp,
                          jnp.zeros(slots, jnp.int32),
                          jnp.zeros(slots, jnp.int32),
                          jnp.zeros((slots, pps), jnp.int32),
                          jnp.zeros(slots, bool))[2]
            jax.block_until_ready(run())  # compile
            row["host_gap_us_per_step"] = _median_wall_us(
                run, iters=iters)
        out[name] = row
    # quantized arm (ISSUE 16): int8 weights + int8 KV pages run the
    # per-op tower (the fused cell is an fp-weight program), so the
    # census to gate is twofold — the quant step stays tower-shaped,
    # and the fp fused path above is UNTOUCHED by the quant code paths
    # (bench.py pins it at its historical launches/step)
    from mxnet_tpu.serving.quantize import quantize_lm
    qparams = quantize_lm(lm, "int8").jax_params()
    out["quant_int8"] = dec.decode_launch_stats(
        qparams, cfg, page_size, slots, pps, total, fused=True,
        layer_group=layer_group, mode=fused_mode, quant=("int8",),
        kv_dtype="int8")
    out["slots"] = slots
    out["num_layers"] = kw["num_layers"]
    return out


def speculative_steplat(measure=True, iters=10, slots=8, page_size=8,
                        ks=(1, 2, 4), model_kw=None):
    """Launches-per-emitted-token census of the speculative wide-verify
    step at several speculation depths, next to the plain decode step.

    The verify program's launch count is STATIC — a property of
    (cfg, page_size, width) fixed at trace time, independent of how
    many drafts the target accepts (acceptance only selects which
    outputs are kept).  At depth k the one launch can emit up to k + 1
    tokens, so ``launches_per_emitted_token`` is the per-token dispatch
    bill at full acceptance; the plain decode row is the k = 0
    baseline.  tests/test_speculative.py gates the census; wall time
    stays informational."""
    from mxnet_tpu.models import decoder as dec

    kw = dict(vocab_size=128, num_layers=2, units=64, hidden_size=128,
              num_heads=4, num_kv_heads=2, max_length=128)
    kw.update(model_kw or {})
    lm = dec.decoder_tiny_lm(seed=0, **kw)
    cfg = lm.config
    params = lm.jax_params()
    pps = (kw["max_length"] + page_size - 1) // page_size
    total = slots * pps + 1

    plain = dec.decode_launch_stats(params, cfg, page_size, slots, pps,
                                    total, fused=False)
    out = {"decode": {
        "launches_per_step": plain["launches_per_step"],
        "launches_per_emitted_token": plain["launches_per_step"]}}
    shape = (cfg.num_layers, cfg.num_kv_heads, total, page_size,
             cfg.head_dim)
    for k in ks:
        width = k + 1
        row = dict(dec.verify_launch_stats(params, cfg, page_size,
                                           width, slots, pps, total))
        if measure:
            fn = dec.make_verify_step(cfg, page_size, width)

            def run(fn=fn, width=width):
                kp = jnp.zeros(shape, jnp.float32)
                vp = jnp.zeros(shape, jnp.float32)
                return fn(params, kp, vp,
                          jnp.zeros((slots, width), jnp.int32),
                          jnp.zeros(slots, jnp.int32),
                          jnp.zeros(slots, jnp.int32),
                          jnp.zeros((slots, pps), jnp.int32),
                          jnp.zeros(slots, bool))[2]
            jax.block_until_ready(run())  # compile
            row["host_gap_us_per_step"] = _median_wall_us(run,
                                                          iters=iters)
        out["k%d" % k] = row
    out["slots"] = slots
    return out


def decode_async_steplat(slots=4, page_size=8, max_new=48, n_requests=8,
                         model_kw=None):
    """Sync vs async DecodeEngine A/B on one greedy workload (ISSUE 17).

    Reports, per mode: end-to-end tokens/sec, inter-token p50, device
    decode-step time (the ``decode_step`` histogram — launch→retire
    wall for async, launch→force for sync), host-gap μs/step (host
    scheduling time exposed between a result landing and the next
    launch — the quantity pipelining hides), and the achieved dispatch
    depth.  Each mode runs the workload once untimed (warm the box —
    first-run wall clock is dominated by cache/turbo transients, which
    otherwise bias the arm that runs second) before the measured pass.
    ``host_cores`` keys the regime: overlap needs a second execution
    unit, so on a 1-core host the async arm's ceiling is parity (total
    work is conserved; the hidden host gap still burns the same core)
    and the honest win signal is the host-gap-share collapse, which is
    what the chip converts into throughput.  Two static properties
    ride along for the tier-1 gate: the async launch census must be
    IDENTICAL to sync (pipelining reorders dispatch, it adds no
    programs) and the emitted token streams must be bit-equal."""
    from mxnet_tpu.models import decoder as dec
    from mxnet_tpu import serving

    kw = dict(vocab_size=128, num_layers=2, units=64, hidden_size=128,
              num_heads=4, num_kv_heads=2, max_length=128)
    kw.update(model_kw or {})
    lm = dec.decoder_tiny_lm(seed=0, **kw)
    prompts = [[(3 * i + j) % 96 + 1 for j in range(4)]
               for i in range(n_requests)]
    # staggered budgets: uniform max_new would finish whole waves at
    # once, draining the pipeline at every boundary and charging the
    # async arm exposed gaps that sustained load never shows
    budgets = [max_new - (7 * i) % 17 for i in range(n_requests)]
    out = {"slots": slots, "max_new": max_new, "requests": n_requests,
           "host_cores": os.cpu_count()}
    census, streams = {}, {}
    for mode, async_on in (("sync", False), ("async", True)):
        eng = serving.DecodeEngine(
            lm, name="steplat", slots=slots, page_size=page_size,
            prefill_chunk=8, max_ctx=kw["max_length"],
            prefix_cache=False, async_decode=async_on)
        try:
            eng.warmup()
            # warm pass: identical workload, untimed — metrics reset
            # after so the measured pass owns the histograms
            for f in [eng.submit(list(p), max_new_tokens=n)
                      for p, n in zip(prompts, budgets)]:
                f.result(timeout=600)
            eng.metrics.reset()
            t0 = time.perf_counter()
            futs = [eng.submit(list(p), max_new_tokens=n)
                    for p, n in zip(prompts, budgets)]
            res = [f.result(timeout=600) for f in futs]
            wall = time.perf_counter() - t0
        finally:
            eng.stop(drain=False)
        census[mode] = dict(eng.launch_stats)
        streams[mode] = [r["tokens"] for r in res]
        m = eng.metrics.snapshot()["models"]["steplat"]
        gen = m["generate"]
        n_tok = sum(len(r["tokens"]) for r in res)
        step_ms = gen["decode_step"].get("mean_ms", 0.0)
        gap_us = gen.get("host_gap_us", {}).get("mean_us", 0.0)
        row = {"tokens_per_sec": round(n_tok / wall, 1),
               "inter_token_p50_ms": gen["inter_token"].get("p50_ms"),
               "device_step_us": round(step_ms * 1e3, 2),
               "host_gap_us_per_step": round(gap_us, 2),
               "host_gap_share": (round(gap_us / (step_ms * 1e3), 4)
                                  if step_ms else None),
               "deferred_reads": m["counters"].get(
                   "deferred_reads_total", 0)}
        if async_on:
            dd = gen.get("dispatch_depth", {})
            row["dispatch_depth_mean"] = dd.get("mean", 0)
            row["dispatch_depth_max"] = dd.get("max", 0)
        out[mode] = row
    out["launch_census_identical"] = census["async"] == census["sync"]
    out["bit_identical_streams"] = streams["async"] == streams["sync"]
    return out


def sharded_steplat(mesh_shape=(4, 2), axis_names=("dp", "tp"), B=8, L=32,
                    units=64, hidden=128, heads=2, measure=True, iters=10,
                    zero=0, remat=None):
    """Collective census + latency of the dp×tp sharded train step.

    Like the launch census, the collective counts are a STATIC property
    of the compiled program (GSPMD inserts them at partitioning time;
    the ZeRO lowering hand-places its reduce-scatter/all-gather):
    deterministic and load-independent, so CI gates on the per-class
    counts (tests/test_sharding.py, tests/test_zero.py) while the wall
    time stays informational.  ``zero``/``remat`` thread the ISSUE-15
    knobs onto the config — the zero-1 dp row's gate is the layout
    proof: grad comm = reduce-scatter + all-gather (one per sharded
    param), the only all-reduce left is the scalar loss mean.  Returns
    {mesh, zero, remat, collectives: {class: n, total},
    host_gap_us_per_step?}.
    """
    from mxnet_tpu.parallel import (ShardingConfig, DataParallelTrainer,
                                    collective_census)
    from mxnet_tpu.models.bert import TransformerLayer
    import mxnet_tpu as mx

    cfg = ShardingConfig.for_transformer(mesh_shape=mesh_shape,
                                         axis_names=axis_names,
                                         zero=zero, remat=remat)
    net = TransformerLayer(units=units, hidden_size=hidden, num_heads=heads,
                           dropout=0.0)
    net.initialize()
    x = mx.np.array(onp.random.RandomState(0)
                    .randn(B, L, units).astype("float32"))
    net(x)  # materialize deferred shapes
    trainer = DataParallelTrainer(
        net, lambda out, y: (out - y) ** 2, "sgd",
        {"learning_rate": 0.1}, sharding=cfg)
    state = trainer.init_state()
    step = trainer.build_step(donate=False)
    xb = x._data
    yb = jnp.zeros_like(xb)
    key = jax.random.key(0)
    lr = jnp.float32(0.1)
    lowered = step.lower(state, xb, yb, key, lr)
    row = {"mesh": cfg.describe(), "zero": zero, "remat": remat,
           "collectives": collective_census(lowered)}
    if measure:
        jax.block_until_ready(step(state, xb, yb, key, lr))  # compile
        row["host_gap_us_per_step"] = _median_wall_us(
            step, state, xb, yb, key, lr, iters=iters)
    return row


def decode_tp_steplat(mesh_shape=(4, 2), axis_names=("dp", "tp"),
                      slots=8, page_size=8, batch_probe=None,
                      fused_mode="interpret", model_kw=None):
    """Collective census of the TENSOR-PARALLEL decode step (ISSUE 13).

    Lowers the dp×tp decode step (tower and fused layer-group variants)
    and counts the GSPMD collectives per class — the static property the
    tier-1 gate asserts: all-reduce ONLY (two per layer, the Megatron
    row-parallel reductions after attention proj and ffn2), every other
    collective class zero, and counts INVARIANT to batch size (KV paging
    and slot scheduling must add no cross-chip traffic as the batch
    grows).  ``batch_probe`` lists the extra slot counts checked for
    invariance (default: 2× the base).  Returns {mesh, tp, tower:
    {collectives}, fused: {collectives}, batch_invariant: bool}.
    """
    from mxnet_tpu.models import decoder as dec
    from mxnet_tpu.parallel.shardcfg import ShardingConfig

    kw = dict(vocab_size=128, num_layers=2, units=64, hidden_size=128,
              num_heads=4, num_kv_heads=2, max_length=128)
    kw.update(model_kw or {})
    lm = dec.decoder_tiny_lm(seed=0, **kw)
    cfg = lm.config
    params = lm.jax_params()
    pps = (kw["max_length"] + page_size - 1) // page_size
    scfg = ShardingConfig.for_transformer(mesh_shape=mesh_shape,
                                          axis_names=axis_names)
    out = {"mesh": scfg.describe(), "tp": scfg.axis_size("tp"),
           "num_layers": kw["num_layers"], "slots": slots}
    probes = list(batch_probe or (slots * 2,))
    invariant = True
    for name, fused in (("tower", False), ("fused", True)):
        stats = dec.decode_collective_stats(
            params, cfg, page_size, slots, pps, slots * pps + 1, scfg,
            fused=fused, mode=fused_mode)
        out[name] = {"collectives": stats["collectives"]}
        for b in probes:
            alt = dec.decode_collective_stats(
                params, cfg, page_size, b, pps, b * pps + 1, scfg,
                fused=fused, mode=fused_mode)
            if alt["collectives"] != stats["collectives"]:
                invariant = False
    out["batch_invariant"] = invariant
    return out


def main():
    result = {
        "backend": jax.default_backend(),
        "lstm": lstm_steplat(),
        "decode": decode_steplat(),
        "speculative": speculative_steplat(),
        "decode_async": decode_async_steplat(),
    }
    sharded = {}
    for name, shape, axes, kw in (
            ("dp8", (8,), ("dp",), {}),
            ("dp4tp2", (4, 2), ("dp", "tp"), {}),
            # ISSUE 15 gate rows: zero-1 dp grad comm must lower to
            # reduce-scatter + all-gather (no grad all-reduce) and remat
            # must not change the collective layout.
            ("dp8_zero1", (8,), ("dp",), {"zero": 1}),
            ("dp8_zero1_remat", (8,), ("dp",),
             {"zero": 1, "remat": "attention"})):
        try:
            sharded[name] = sharded_steplat(shape, axes, **kw)
        except ValueError as e:  # mesh doesn't fit this host
            sharded[name] = {"skipped": str(e)[:120]}
    result["sharded"] = sharded
    try:
        result["decode_tp"] = decode_tp_steplat()
    except ValueError as e:  # mesh doesn't fit this host
        result["decode_tp"] = {"skipped": str(e)[:120]}
    print(json.dumps(result))


if __name__ == "__main__":
    main()
